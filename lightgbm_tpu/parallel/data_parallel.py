"""Data-parallel tree learning over a `jax.sharding.Mesh`.

TPU-native re-design of the reference `DataParallelTreeLearner`
(`src/treelearner/data_parallel_tree_learner.cpp`): rows are sharded in
contiguous blocks over a 1-D ``("data",)`` mesh axis; each shard keeps a
LOCAL leaf partition (its slice of every leaf's rows) and builds local
histograms, which are summed across shards with `lax.psum` inside
`shard_map` — the XLA-collective replacement for
`Network::ReduceScatter(SumReducer)` + `SyncUpGlobalBestSplit`
(data_parallel_tree_learner.cpp:149-164, parallel_tree_learner.h:190-213).
Because every shard then holds the full GLOBAL histogram, split selection is
computed redundantly and bit-identically on all shards, so no second
collective is needed; only global leaf counts (the reference's
`global_data_count_in_leaf_`) ride along in the tree-build state.

The whole tree still grows in ONE jitted SPMD program (zero mid-tree host
syncs); `jit` + `shard_map` partitions it over the mesh, and XLA lowers the
psums to ICI all-reduces on real hardware.
"""
from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config import Config
from ..dist import shard_map as dist_shard_map
from ..io.dataset import Dataset
from ..models.device_learner import DeviceTreeLearner, TreeRecord, _pow2ceil


def default_mesh(num_shards: Optional[int] = None,
                 axis_name: str = "data") -> Mesh:
    devs = jax.devices()
    if num_shards is not None:
        devs = devs[:num_shards]
    return Mesh(np.asarray(devs), (axis_name,))


class DataParallelTreeLearner:
    """Rows-sharded fused tree learner; same train() surface as
    `DeviceTreeLearner` so the GBDT driver is parallelism-agnostic
    (the reference crosses {serial,data,...}x{cpu,gpu} the same way,
    tree_learner.cpp:13-36)."""

    mode = "data"

    def __init__(self, cfg: Config, dataset: Dataset,
                 mesh: Optional[Mesh] = None) -> None:
        self.axis_name = "data"
        self.mesh = mesh if mesh is not None else default_mesh(
            cfg.num_machines if cfg.num_machines > 1 else None,
            self.axis_name)
        self.nd = int(self.mesh.devices.size)
        self.inner = DeviceTreeLearner(cfg, dataset, axis_name=self.axis_name,
                                       parallel_mode=self.mode,
                                       mesh_size=self.nd)
        # the aligned engine shard_maps its programs over this mesh
        self.inner._mesh = self.mesh
        self.cfg = cfg
        self.ds = dataset
        n = dataset.num_data
        self.n = n
        self.per_shard = int(math.ceil(n / self.nd))
        self.local_pad = max(_pow2ceil(self.per_shard), self.inner.min_pad)
        self.local_idx_len = self.per_shard + self.local_pad
        self.pad_rows = self.nd * self.per_shard - n

        # sharded placement comes from the Dataset-level cache so an
        # early loader/CLI shard() and the learner share device buffers
        placed = dataset.shard(self.mesh, self.axis_name)
        self.bins_sharded = placed["bins"]
        self.bins_T_sharded = placed["bins_T"]
        self._row_shard = NamedSharding(self.mesh, P(self.axis_name))
        self._fn_cache = {}

    # --- delegation: GBDT uses these off the learner ------------------
    def __getattr__(self, name):
        if name == "inner":
            raise AttributeError(name)
        return getattr(self.inner, name)

    # ------------------------------------------------------------------
    def init_root_partition(self, bag_indices: Optional[np.ndarray],
                            bag_cnt: int) -> Tuple[jax.Array, jax.Array]:
        """Per-shard local partitions: shard s owns global rows
        [s*per, (s+1)*per); local indices are block-relative. The no-bagging
        identity partition is built ON DEVICE (a fresh iota per call — the
        train step donates/consumes the buffer), avoiding a per-tree
        host build + transfer."""
        if bag_indices is None:
            fn = self._fn_cache.get("identity_part")
            if fn is None:
                nd, per, llen, n = (self.nd, self.per_shard,
                                    self.local_idx_len, self.n)
                shard = self._row_shard

                def make():
                    pos = jnp.arange(nd * llen, dtype=jnp.int32)
                    local = pos % llen
                    s = pos // llen
                    cnt = jnp.minimum(
                        jnp.maximum(n - jnp.arange(nd, dtype=jnp.int32) * per,
                                    0), per)
                    idxs = jnp.where(local < cnt[s], local, 0)
                    return idxs, cnt

                fn = jax.jit(make, out_shardings=(shard, shard))
                self._fn_cache["identity_part"] = fn
            return fn()
        idxs = np.zeros((self.nd, self.local_idx_len), np.int32)
        counts = np.zeros(self.nd, np.int32)
        for s in range(self.nd):
            lo, hi = s * self.per_shard, (s + 1) * self.per_shard
            sel = bag_indices[(bag_indices >= lo) & (bag_indices < hi)]
            c = len(sel)
            idxs[s, :c] = (sel - lo).astype(np.int32)
            counts[s] = c
        shard = self._row_shard
        return (jax.device_put(idxs.reshape(-1), shard),
                jax.device_put(counts, shard))

    # ------------------------------------------------------------------
    def _sharded_train_fn(self, root_contiguous: bool):
        key = (self.local_pad, root_contiguous)
        fn = self._fn_cache.get(key)
        if fn is not None:
            return fn
        build = self.inner._make_build_fn(self.local_pad, root_contiguous)
        ax = self.axis_name
        # per-shard partition state (leaf_begin/leaf_cnt_part) stays sharded;
        # everything else is replicated (identical on every shard)
        rec_specs = TreeRecord(
            num_splits=P(), leaf=P(), feature=P(), threshold_bin=P(),
            default_left=P(), is_cat=P(), cat_bitset=P(), left_output=P(),
            right_output=P(), left_count=P(), right_count=P(), gain=P(),
            internal_value=P(), leaf_value=P(), leaf_count_arr=P(),
            leaf_begin=P(ax), leaf_cnt_part=P(ax))

        if root_contiguous:
            mapped = dist_shard_map(
                build, mesh=self.mesh,
                in_specs=(P(ax), P(None, ax), P(ax), P(ax), P()),
                out_specs=(P(ax), rec_specs),
                check_vma=False)

            def run_fresh(bins, bins_T, grad, hess, fmask):
                pad = self.nd * self.per_shard - grad.shape[0]
                if pad:
                    grad = jnp.pad(grad, (0, pad))
                    hess = jnp.pad(hess, (0, pad))
                return mapped(bins, bins_T, grad, hess, fmask)

            fn = jax.jit(run_fresh)
            self._fn_cache[key] = fn
            return fn

        def per_shard(bins, bins_T, indices, grad, hess, counts, fmask):
            return build(bins, bins_T, indices, grad, hess, counts[0], fmask)

        mapped = dist_shard_map(
            per_shard, mesh=self.mesh,
            in_specs=(P(ax), P(None, ax), P(ax), P(ax), P(ax), P(ax), P()),
            out_specs=(P(ax), rec_specs),
            check_vma=False)

        def run(bins, bins_T, indices, grad, hess, counts, fmask):
            pad = self.nd * self.per_shard - grad.shape[0]
            if pad:
                grad = jnp.pad(grad, (0, pad))
                hess = jnp.pad(hess, (0, pad))
            return mapped(bins, bins_T, indices, grad, hess, counts, fmask)

        fn = jax.jit(run, donate_argnums=(2,))
        self._fn_cache[key] = fn
        return fn

    # ------------------------------------------------------------------
    def _score_fn(self):
        fn = self._fn_cache.get("score")
        if fn is not None:
            return fn
        ax = self.axis_name
        from ..models.device_learner import traverse_record

        def per_shard(score, bins, trav, nb, db, mt, scale):
            leaves = traverse_record(bins, trav, nb, db, mt)
            return score + scale * trav["leaf_value"][leaves]

        mapped = dist_shard_map(
            per_shard, mesh=self.mesh,
            in_specs=(P(ax), P(ax), P(), P(), P(), P(), P()),
            out_specs=P(ax), check_vma=False)

        def run(score_row, trav, scale):
            pad = self.nd * self.per_shard - score_row.shape[0]
            padded = jnp.pad(score_row, (0, pad)) if pad else score_row
            out = mapped(padded, self.bins_sharded, trav,
                         self.inner._nb_dev, self.inner._db_dev,
                         self.inner._mt_dev, scale)
            return out[:score_row.shape[0]] if pad else out

        fn = jax.jit(run)
        self._fn_cache["score"] = fn
        return fn

    def add_score(self, score_row: jax.Array, trav, scale: float) -> jax.Array:
        """Sharded score update: each shard traverses only its row block."""
        return self._score_fn()(score_row, trav, jnp.float32(scale))

    def _partition_score_fn(self):
        fn = self._fn_cache.get("pscore")
        if fn is not None:
            return fn
        ax = self.axis_name
        from jax import lax

        from ..ops.partition import leaf_value_fill, unpermute_to_rows
        local_len = self.local_idx_len
        per = self.per_shard
        n = self.n

        def per_shard(score, leaf_begin, leaf_cnt, leaf_value, indices,
                      scale):
            s = lax.axis_index(ax)
            cnt = jnp.clip(n - s * per, 0, per).astype(jnp.int32)
            fill = leaf_value_fill(leaf_begin, leaf_cnt, leaf_value, per)
            delta = unpermute_to_rows(indices[:per], fill, cnt, per)
            return score + scale * delta

        mapped = dist_shard_map(
            per_shard, mesh=self.mesh,
            in_specs=(P(ax), P(ax), P(ax), P(), P(ax), P()),
            out_specs=P(ax), check_vma=False)

        def run(score_row, leaf_begin, leaf_cnt, leaf_value, indices, scale):
            pad = self.nd * per - score_row.shape[0]
            padded = jnp.pad(score_row, (0, pad)) if pad else score_row
            out = mapped(padded, leaf_begin, leaf_cnt, leaf_value, indices,
                         scale)
            return out[:score_row.shape[0]] if pad else out

        fn = jax.jit(run)
        self._fn_cache["pscore"] = fn
        return fn

    def add_score_from_partition(self, score: jax.Array, class_id: int,
                                 record: TreeRecord, indices: jax.Array,
                                 scale: float) -> jax.Array:
        """Partition-based score update, per shard: leaf fill over the local
        partition + one key-sort back to the shard's row-block order.
        Level-built records score through their (finer) block tables."""
        if record.block_begin is not None:
            row = self._partition_score_fn()(
                score[class_id], record.block_begin, record.block_cnt,
                jnp.asarray(record.block_value, jnp.float32), indices,
                jnp.float32(scale))
        else:
            row = self._partition_score_fn()(
                score[class_id], record.leaf_begin, record.leaf_cnt_part,
                record.leaf_value, indices, jnp.float32(scale))
        return score.at[class_id].set(row)

    # ------------------------------------------------------------------
    def train(self, grad: jax.Array, hess: jax.Array, indices: jax.Array,
              counts: jax.Array, feature_mask: Optional[np.ndarray] = None
              ) -> Tuple[jax.Array, TreeRecord]:
        fn = self._sharded_train_fn(False)
        return fn(self.bins_sharded, self.bins_T_sharded, indices, grad,
                  hess, counts, self.inner._fmask_arr(feature_mask))

    def train_fresh(self, grad: jax.Array, hess: jax.Array,
                    feature_mask: Optional[np.ndarray] = None
                    ) -> Tuple[jax.Array, TreeRecord]:
        if self.inner.level_mode_ok():
            from ..models.level_builder import replay_leafwise
            fn = self._sharded_level_fn()
            spec = fn(self._words_sharded(), grad, hess,
                      self.inner._fmask_arr(feature_mask))
            host = jax.device_get(spec._replace(rid=None))
            # leafI is per-shard [nd*S, w]; global lanes are identical, so
            # shard 0's slice serves the replay
            S = host.bestF.shape[0]
            host = host._replace(leafI=host.leafI[:S],
                                 block_begin=host.block_begin[:S],
                                 block_cnt=host.block_cnt[:S])
            rec, exact = replay_leafwise(host, self.cfg.num_leaves)
            if exact:
                rec = rec._replace(block_begin=spec.block_begin,
                                   block_cnt=spec.block_cnt)
                return spec.rid, rec
            self.inner._level_fallbacks = getattr(
                self.inner, "_level_fallbacks", 0) + 1
        fn = self._sharded_train_fn(True)
        return fn(self.bins_sharded, self.bins_T_sharded, grad, hess,
                  self.inner._fmask_arr(feature_mask))

    # ------------------------------------------------------------------
    def _words_sharded(self) -> jax.Array:
        w = self._fn_cache.get("words")
        if w is None:
            from ..models.level_builder import pack_bin_words
            bins_np = np.asarray(self.ds.bins)
            if self.inner.num_features != self.inner.num_real_features:
                pad_f = self.inner.num_features - self.inner.num_real_features
                bins_np = np.pad(bins_np, ((0, 0), (0, pad_f)))
            if self.pad_rows:
                bins_np = np.pad(bins_np, ((0, self.pad_rows), (0, 0)))
            w = jax.device_put(
                pack_bin_words(bins_np),
                NamedSharding(self.mesh, P(None, self.axis_name)))
            self._fn_cache["words"] = w
        return w

    def _sharded_level_fn(self):
        fn = self._fn_cache.get("level")
        if fn is not None:
            return fn
        from ..models.level_builder import SpecResult, make_level_build_fn
        build = make_level_build_fn(self.inner)
        ax = self.axis_name
        # split decisions are identical on every shard (global histograms);
        # only the physical partition state is shard-local
        spec_specs = SpecResult(
            rid=P(ax), n_exec=P(), execF=P(), execI=P(), execB=P(),
            bestF=P(), bestI=P(), bestB=P(), leafF=P(), leafI=P(ax),
            block_begin=P(ax), block_cnt=P(ax))
        mapped = dist_shard_map(
            build, mesh=self.mesh,
            in_specs=(P(None, ax), P(ax), P(ax), P()),
            out_specs=spec_specs,
            check_vma=False)

        def run(words, grad, hess, fmask):
            pad = self.nd * self.per_shard - grad.shape[0]
            if pad:
                grad = jnp.pad(grad, (0, pad))
                hess = jnp.pad(hess, (0, pad))
            return mapped(words, grad, hess, fmask)

        fn = jax.jit(run)
        self._fn_cache["level"] = fn
        return fn
