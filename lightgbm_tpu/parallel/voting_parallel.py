"""Voting-parallel (PV-Tree) learning over a `jax.sharding.Mesh`.

TPU-native re-design of the reference `VotingParallelTreeLearner`
(`src/treelearner/voting_parallel_tree_learner.cpp`): rows are sharded like
the data-parallel learner, but instead of reducing FULL histograms across
shards, each shard runs a relaxed LOCAL split search on its own histograms,
votes its top-k features (`top_k` config), the votes are globally summed
(`GlobalVoting` `:170-200`), and only the elected ~2k features' histograms
are cross-shard reduced before the global best-split search
(`FindBestSplits` `:262-400`) — cutting the per-split collective volume from
O(F*B) to O(top_k*B).

All of that runs inside the same fused whole-tree program: see the
``mode == "voting"`` eval path in
`lightgbm_tpu/models/device_learner.py` (`_make_build_fn`); this wrapper
only selects the mode — the row sharding, score updates, and partition
bookkeeping are identical to the data-parallel learner.
"""
from __future__ import annotations

from .data_parallel import DataParallelTreeLearner


class VotingParallelTreeLearner(DataParallelTreeLearner):
    """Rows-sharded learner with top-k feature voting collectives."""

    mode = "voting"
