"""Feature-parallel tree learning over a `jax.sharding.Mesh`.

TPU-native re-design of the reference `FeatureParallelTreeLearner`
(`src/treelearner/feature_parallel_tree_learner.cpp`): every shard holds ALL
rows (the reference's "every worker holds all data" premise, `:33-52`), but
histogram construction — the dominant cost — is divided by contiguous
feature blocks: shard i builds the histograms of features
``[i*F/nd, (i+1)*F/nd)`` and one `lax.psum` assembles the full global
histogram on every shard. Because each shard then holds identical global
state, the best split is found redundantly and bit-identically everywhere —
the histogram reduce subsumes the reference's `SyncUpGlobalBestSplit`
allreduce (`:55-71`, `parallel_tree_learner.h:190-213`) — and the partition
update is computed locally with no further communication, exactly like the
reference workers each applying the synced split.

The feature axis is zero-padded to a multiple of the mesh size; padded
features are trivial (masked out of every search).
"""
from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config import Config
from ..dist import shard_map as dist_shard_map
from ..io.dataset import Dataset
from ..models.device_learner import DeviceTreeLearner, TreeRecord, _pow2ceil
from .data_parallel import default_mesh


class FeatureParallelTreeLearner:
    """Feature-blocks-sharded fused tree learner; same train() surface as
    `DeviceTreeLearner` (the factory axis of tree_learner.cpp:13-36)."""

    def __init__(self, cfg: Config, dataset: Dataset,
                 mesh: Optional[Mesh] = None) -> None:
        self.axis_name = "feature"
        self.mesh = mesh if mesh is not None else default_mesh(
            cfg.num_machines if cfg.num_machines > 1 else None,
            self.axis_name)
        self.nd = int(self.mesh.devices.size)
        f = dataset.num_features
        f_pad = int(math.ceil(max(f, 1) / self.nd)) * self.nd
        self.inner = DeviceTreeLearner(cfg, dataset,
                                       axis_name=self.axis_name,
                                       parallel_mode="feature",
                                       feature_pad_to=f_pad,
                                       mesh_size=self.nd)
        self.cfg = cfg
        self.ds = dataset
        self.n = dataset.num_data
        bins_np = np.asarray(dataset.bins)
        if f_pad > f:
            bins_np = np.pad(bins_np, ((0, 0), (0, f_pad - f)))
        # rows replicated on every shard (reference: full data per worker)
        self.bins_repl = jax.device_put(
            bins_np, NamedSharding(self.mesh, P()))
        self._fn_cache = {}

    # --- delegation: GBDT uses these off the learner ------------------
    def __getattr__(self, name):
        if name == "inner":
            raise AttributeError(name)
        return getattr(self.inner, name)

    # ------------------------------------------------------------------
    def init_root_partition(self, bag_indices: Optional[np.ndarray],
                            bag_cnt: int):
        """Replicated full-row partition (identical on every shard)."""
        return self.inner.init_root_partition(bag_indices, bag_cnt)

    # ------------------------------------------------------------------
    def _sharded_train_fn(self, root_padded: int, root_contiguous: bool):
        key = (root_padded, root_contiguous)
        fn = self._fn_cache.get(key)
        if fn is not None:
            return fn
        build = self.inner._make_build_fn(root_padded, root_contiguous)
        rec_specs = TreeRecord(*([P()] * len(TreeRecord._fields)))
        n_in = 5 if root_contiguous else 7
        mapped = dist_shard_map(
            build, mesh=self.mesh,
            in_specs=tuple([P()] * n_in),
            out_specs=(P(), rec_specs),
            check_vma=False)
        fn = jax.jit(mapped)
        self._fn_cache[key] = fn
        return fn

    def add_score(self, score_row: jax.Array, trav, scale: float) -> jax.Array:
        return self.inner.add_score(score_row, trav, scale)

    # ------------------------------------------------------------------
    def train(self, grad: jax.Array, hess: jax.Array, indices: jax.Array,
              root_count: int, feature_mask: Optional[np.ndarray] = None
              ) -> Tuple[jax.Array, TreeRecord]:
        root_padded = max(_pow2ceil(int(root_count)), self.inner.min_pad)
        if feature_mask is None:
            feature_mask = self.inner.feature_mask()
        fn = self._sharded_train_fn(root_padded, False)
        return fn(self.bins_repl, self.inner.bins_T_dev, indices, grad, hess,
                  jnp.int32(root_count), self.inner._fmask_arr(feature_mask))

    def train_fresh(self, grad: jax.Array, hess: jax.Array,
                    feature_mask: Optional[np.ndarray] = None
                    ) -> Tuple[jax.Array, TreeRecord]:
        root_padded = max(_pow2ceil(self.n), self.inner.min_pad)
        if feature_mask is None:
            feature_mask = self.inner.feature_mask()
        fn = self._sharded_train_fn(root_padded, True)
        return fn(self.bins_repl, self.inner.bins_T_dev, grad, hess,
                  self.inner._fmask_arr(feature_mask))
