"""Distributed tree learning over `jax.sharding.Mesh` — the XLA-collective
replacement for the reference's `src/network/` + parallel tree learners."""
from .data_parallel import DataParallelTreeLearner, default_mesh

__all__ = ["DataParallelTreeLearner", "default_mesh"]
