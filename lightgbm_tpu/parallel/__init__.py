"""Parallel tree learners over a `jax.sharding.Mesh` — the XLA-collective
replacement for the reference's `src/network/` + parallel tree learners.

`make_parallel_learner` is the factory axis the distributed runtime
(`dist/runtime.py`) calls — the analogue of
`TreeLearner::CreateTreeLearner` (tree_learner.cpp:13-36).
"""
from __future__ import annotations

from .data_parallel import DataParallelTreeLearner, default_mesh
from .feature_parallel import FeatureParallelTreeLearner
from .voting_parallel import VotingParallelTreeLearner

__all__ = [
    "DataParallelTreeLearner",
    "FeatureParallelTreeLearner",
    "VotingParallelTreeLearner",
    "default_mesh",
    "make_parallel_learner",
]

_LEARNERS = {
    "data": DataParallelTreeLearner,
    "feature": FeatureParallelTreeLearner,
    "voting": VotingParallelTreeLearner,
}


def make_parallel_learner(cfg, dataset, mesh=None):
    """Construct the parallel learner selected by ``cfg.tree_learner``.

    mesh: optional pre-built `jax.sharding.Mesh`; each learner builds its
    own default mesh over the visible devices when omitted.
    """
    try:
        cls = _LEARNERS[cfg.tree_learner]
    except KeyError:
        raise ValueError(
            f"tree_learner={cfg.tree_learner!r} has no parallel learner "
            f"(expected one of {sorted(_LEARNERS)})") from None
    return cls(cfg, dataset, mesh=mesh)
