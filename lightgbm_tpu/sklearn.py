"""scikit-learn estimator wrappers.

Re-creates `python-package/lightgbm/sklearn.py`: `LGBMModel` base +
`LGBMRegressor` / `LGBMClassifier` / `LGBMRanker`, with fit/predict,
eval sets, early stopping, feature importances, and sklearn get/set_params
compatibility.
"""
from __future__ import annotations

import copy
from typing import Any, Callable, Dict, List, Optional, Union

import numpy as np

from .basic import Booster, Dataset, LightGBMError
from .engine import train

try:
    from sklearn.base import BaseEstimator as _SKBase
    from sklearn.base import ClassifierMixin as _SKClassifier
    from sklearn.base import RegressorMixin as _SKRegressor
    _HAS_SKLEARN = True
except Exception:  # pragma: no cover - sklearn optional
    _SKBase = object

    class _SKClassifier:  # type: ignore
        pass

    class _SKRegressor:  # type: ignore
        pass
    _HAS_SKLEARN = False

# the conformance validation helpers need sklearn >= 1.6 (validate_data
# with ensure_all_finite); older versions keep the permissive pre-1.6
# behavior rather than crashing every fit/predict
try:
    from sklearn.utils.validation import validate_data as _sk_validate_data
except Exception:  # pragma: no cover - old sklearn
    _sk_validate_data = None


class _ObjectiveFunctionWrapper:
    """Wrap sklearn-style fobj(y_true, y_pred) into engine fobj
    (reference sklearn.py:33-110)."""

    def __init__(self, func: Callable) -> None:
        self.func = func

    def __call__(self, preds, dataset):
        labels = dataset.get_label()
        argc = self.func.__code__.co_argcount
        if argc == 2:
            grad, hess = self.func(labels, preds)
        elif argc == 3:
            grad, hess = self.func(labels, preds, dataset.get_group())
        else:
            raise TypeError(f"Self-defined objective should have 2 or 3 "
                            f"arguments, got {argc}")
        return grad, hess


class _EvalFunctionWrapper:
    """Wrap sklearn-style feval (reference sklearn.py:112-185)."""

    def __init__(self, func: Callable) -> None:
        self.func = func

    def __call__(self, preds, dataset):
        labels = dataset.get_label()
        argc = self.func.__code__.co_argcount
        if argc == 3:
            return self.func(labels, preds, dataset.get_weight())
        if argc == 4:
            return self.func(labels, preds, dataset.get_weight(),
                             dataset.get_group())
        return self.func(labels, preds)


class LGBMModel(_SKBase):
    """Base sklearn estimator (reference sklearn.py:187+)."""

    def __init__(self, boosting_type: str = "gbdt", num_leaves: int = 31,
                 max_depth: int = -1, learning_rate: float = 0.1,
                 n_estimators: int = 100, subsample_for_bin: int = 200000,
                 objective: Optional[str] = None,
                 class_weight: Optional[Union[Dict, str]] = None,
                 min_split_gain: float = 0.0, min_child_weight: float = 1e-3,
                 min_child_samples: int = 20, subsample: float = 1.0,
                 subsample_freq: int = 0, colsample_bytree: float = 1.0,
                 reg_alpha: float = 0.0, reg_lambda: float = 0.0,
                 random_state: Optional[int] = None, n_jobs: int = -1,
                 silent: bool = True, importance_type: str = "split",
                 **kwargs: Any) -> None:
        self.boosting_type = boosting_type
        self.num_leaves = num_leaves
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.n_estimators = n_estimators
        self.subsample_for_bin = subsample_for_bin
        self.objective = objective
        self.class_weight = class_weight
        self.min_split_gain = min_split_gain
        self.min_child_weight = min_child_weight
        self.min_child_samples = min_child_samples
        self.subsample = subsample
        self.subsample_freq = subsample_freq
        self.colsample_bytree = colsample_bytree
        self.reg_alpha = reg_alpha
        self.reg_lambda = reg_lambda
        self.random_state = random_state
        self.n_jobs = n_jobs
        self.silent = silent
        self.importance_type = importance_type
        self._other_params: Dict[str, Any] = dict(kwargs)
        self._Booster: Optional[Booster] = None
        self._evals_result: Dict = {}
        self._best_score: Dict = {}
        self._best_iteration = -1
        self._n_features = -1
        self._classes = None
        self._n_classes = -1
        self._objective = objective
        self._fobj = None

    # sklearn plumbing ---------------------------------------------------
    def get_params(self, deep: bool = True) -> Dict[str, Any]:
        params = {
            "boosting_type": self.boosting_type,
            "num_leaves": self.num_leaves, "max_depth": self.max_depth,
            "learning_rate": self.learning_rate,
            "n_estimators": self.n_estimators,
            "subsample_for_bin": self.subsample_for_bin,
            "objective": self.objective, "class_weight": self.class_weight,
            "min_split_gain": self.min_split_gain,
            "min_child_weight": self.min_child_weight,
            "min_child_samples": self.min_child_samples,
            "subsample": self.subsample,
            "subsample_freq": self.subsample_freq,
            "colsample_bytree": self.colsample_bytree,
            "reg_alpha": self.reg_alpha, "reg_lambda": self.reg_lambda,
            "random_state": self.random_state, "n_jobs": self.n_jobs,
            "silent": self.silent, "importance_type": self.importance_type,
        }
        params.update(self._other_params)
        return params

    def set_params(self, **params: Any) -> "LGBMModel":
        for key, value in params.items():
            if hasattr(self, key):
                setattr(self, key, value)
            self._other_params[key] = value
        return self

    # sklearn conformance (check_estimator; reference
    # tests/python_package_test/test_sklearn.py:202) ---------------------
    def __sklearn_is_fitted__(self) -> bool:
        return self._Booster is not None

    if _HAS_SKLEARN:
        def __sklearn_tags__(self):
            tags = super().__sklearn_tags__()
            tags.input_tags.allow_nan = True    # NaN = missing value
            tags.input_tags.sparse = True       # CSR/CSC ingest
            return tags

    def _sk_validate_fit(self, X, y, classifier: bool = False):
        """sklearn-style input validation (sets n_features_in_, rejects
        complex/empty/inf input). DataFrames skip it to preserve the
        categorical-dtype handling; y stays as given for ranking."""
        if _sk_validate_data is None or hasattr(X, "columns"):
            self.n_features_in_ = np.asarray(X).shape[1]
            return X, np.asarray(y).reshape(-1)
        X, y = _sk_validate_data(self, X, y,
                                 accept_sparse=["csr", "csc"],
                                 ensure_all_finite="allow-nan",
                                 dtype=np.float64, multi_output=False)
        if classifier:
            from sklearn.utils.multiclass import check_classification_targets
            check_classification_targets(y)
        return X, y

    def _sk_validate_predict(self, X):
        if not _HAS_SKLEARN:
            return X
        from sklearn.exceptions import NotFittedError
        if self._Booster is None:
            raise NotFittedError(
                "This estimator is not fitted yet. Call 'fit' first.")
        if _sk_validate_data is None or hasattr(X, "columns") \
                or isinstance(X, str):
            return X
        return _sk_validate_data(self, X, accept_sparse=["csr", "csc"],
                                 ensure_all_finite="allow-nan",
                                 dtype=np.float64, reset=False)

    # ------------------------------------------------------------------
    def _make_train_params(self) -> Dict[str, Any]:
        params = self.get_params()
        params.pop("silent", None)
        params.pop("importance_type", None)
        params.pop("n_estimators", None)
        params.pop("class_weight", None)
        if callable(self.objective):
            self._fobj = _ObjectiveFunctionWrapper(self.objective)
            params["objective"] = "none"
        else:
            self._fobj = None
            params["objective"] = self._objective or "regression"
        if self.random_state is not None:
            params["seed"] = self.random_state
            params["bagging_seed"] = self.random_state
            params["feature_fraction_seed"] = self.random_state
            params["drop_seed"] = self.random_state
            params["data_random_seed"] = self.random_state
        params["verbose"] = -1 if self.silent else 1
        # alias mapping sklearn -> native
        params["bagging_fraction"] = params.pop("subsample")
        params["bagging_freq"] = params.pop("subsample_freq")
        params["feature_fraction"] = params.pop("colsample_bytree")
        params["lambda_l1"] = params.pop("reg_alpha")
        params["lambda_l2"] = params.pop("reg_lambda")
        params["min_gain_to_split"] = params.pop("min_split_gain")
        params["min_sum_hessian_in_leaf"] = params.pop("min_child_weight")
        params["min_data_in_leaf"] = params.pop("min_child_samples")
        params["bin_construct_sample_cnt"] = params.pop("subsample_for_bin")
        params["boosting"] = params.pop("boosting_type")
        params.pop("random_state", None)
        params.pop("n_jobs", None)
        return params

    def _sample_weight_with_class_weight(self, y, sample_weight):
        if self.class_weight is None:
            return sample_weight
        classes, counts = np.unique(y, return_counts=True)
        if self.class_weight == "balanced":
            wmap = {c: len(y) / (len(classes) * cnt)
                    for c, cnt in zip(classes, counts)}
        else:
            wmap = dict(self.class_weight)
        cw = np.asarray([wmap.get(v, 1.0) for v in y], np.float64)
        if sample_weight is None:
            return cw
        return cw * np.asarray(sample_weight, np.float64)

    def fit(self, X, y, sample_weight=None, init_score=None, group=None,
            eval_set=None, eval_names=None, eval_sample_weight=None,
            eval_class_weight=None, eval_init_score=None, eval_group=None,
            eval_metric=None, early_stopping_rounds=None, verbose=False,
            feature_name="auto", categorical_feature="auto",
            callbacks=None) -> "LGBMModel":
        params = self._make_train_params()
        if eval_metric is not None and not callable(eval_metric):
            params["metric"] = eval_metric
        if not getattr(self, "_sk_prevalidated", False):
            X, y = self._sk_validate_fit(X, y)
        y = np.asarray(y).reshape(-1)
        sample_weight = self._sample_weight_with_class_weight(y, sample_weight)
        train_set = Dataset(X, label=y, weight=sample_weight, group=group,
                            init_score=init_score, params=params,
                            feature_name=feature_name,
                            categorical_feature=categorical_feature)
        valid_sets = []
        if eval_set is not None:
            if isinstance(eval_set, tuple):
                eval_set = [eval_set]
            for i, (vx, vy) in enumerate(eval_set):
                if vx is X and vy is y:
                    valid_sets.append(train_set)
                    continue
                vw = (eval_sample_weight[i]
                      if eval_sample_weight is not None else None)
                vg = eval_group[i] if eval_group is not None else None
                vi = (eval_init_score[i]
                      if eval_init_score is not None else None)
                valid_sets.append(Dataset(
                    vx, label=np.asarray(vy).reshape(-1), weight=vw,
                    group=vg, init_score=vi, reference=train_set,
                    params=params))
        feval = (_EvalFunctionWrapper(eval_metric)
                 if callable(eval_metric) else None)
        self._evals_result = {}
        self._Booster = train(
            params, train_set, num_boost_round=self.n_estimators,
            valid_sets=valid_sets or None, valid_names=eval_names,
            fobj=self._fobj, feval=feval,
            early_stopping_rounds=early_stopping_rounds,
            evals_result=self._evals_result, verbose_eval=verbose,
            callbacks=callbacks)
        self._n_features = (X.shape[1] if hasattr(X, "shape")
                            else np.asarray(X).shape[1])
        self.n_features_in_ = self._n_features
        self._best_iteration = self._Booster.best_iteration
        self._best_score = self._Booster.best_score
        return self

    def predict(self, X, raw_score=False, num_iteration=None,
                pred_leaf=False, pred_contrib=False, start_iteration=0,
                **kwargs):
        X = self._sk_validate_predict(X)   # raises NotFittedError
        if self._Booster is None:
            raise LightGBMError("Estimator not fitted")
        return self._Booster.predict(X, raw_score=raw_score,
                                     num_iteration=num_iteration,
                                     pred_leaf=pred_leaf,
                                     pred_contrib=pred_contrib,
                                     start_iteration=start_iteration,
                                     **kwargs)

    # properties ---------------------------------------------------------
    @property
    def booster_(self) -> Booster:
        if self._Booster is None:
            raise LightGBMError("No booster found, need to call fit first")
        return self._Booster

    @property
    def best_iteration_(self) -> int:
        return self._best_iteration

    @property
    def best_score_(self) -> Dict:
        return self._best_score

    @property
    def evals_result_(self) -> Dict:
        return self._evals_result

    @property
    def n_features_(self) -> int:
        return self._n_features

    @property
    def feature_importances_(self) -> np.ndarray:
        return self.booster_.feature_importance(self.importance_type)

    @property
    def objective_(self):
        return self._objective


class LGBMRegressor(_SKRegressor, LGBMModel):
    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if self._objective is None:
            self._objective = "regression"

    def fit(self, X, y, **kwargs) -> "LGBMRegressor":
        super().fit(X, y, **kwargs)
        return self


class LGBMClassifier(_SKClassifier, LGBMModel):
    def fit(self, X, y, **kwargs) -> "LGBMClassifier":
        X, y = self._sk_validate_fit(X, y, classifier=True)
        self._sk_prevalidated = True
        y = np.asarray(y).reshape(-1)
        self._classes, y_enc = np.unique(y, return_inverse=True)
        self._n_classes = len(self._classes)
        if self._objective is None or not callable(self._objective):
            if self._n_classes > 2:
                self._objective = self.objective or "multiclass"
                self._other_params["num_class"] = self._n_classes
            else:
                self._objective = self.objective or "binary"
        # re-map eval sets' labels
        if "eval_set" in kwargs and kwargs["eval_set"] is not None:
            es = kwargs["eval_set"]
            if isinstance(es, tuple):
                es = [es]
            label_map = {c: i for i, c in enumerate(self._classes)}
            kwargs["eval_set"] = [
                (vx, np.asarray([label_map[v] for v in np.asarray(vy)]))
                for vx, vy in es]
        try:
            super().fit(X, y_enc.astype(np.float64), **kwargs)
        finally:
            self._sk_prevalidated = False
        return self

    def predict(self, X, raw_score=False, num_iteration=None,
                pred_leaf=False, pred_contrib=False, start_iteration=0,
                **kwargs):
        result = self.predict_proba(X, raw_score=raw_score,
                                    num_iteration=num_iteration,
                                    pred_leaf=pred_leaf,
                                    pred_contrib=pred_contrib,
                                    start_iteration=start_iteration,
                                    **kwargs)
        if raw_score or pred_leaf or pred_contrib:
            return result
        return self._classes[np.argmax(result, axis=1)]

    def predict_proba(self, X, raw_score=False, num_iteration=None,
                      pred_leaf=False, pred_contrib=False,
                      start_iteration=0, **kwargs):
        result = super().predict(X, raw_score=raw_score,
                                 num_iteration=num_iteration,
                                 pred_leaf=pred_leaf,
                                 pred_contrib=pred_contrib,
                                 start_iteration=start_iteration,
                                 **kwargs)
        if raw_score or pred_leaf or pred_contrib:
            return result
        if self._n_classes <= 2 and result.ndim == 1:
            return np.vstack([1.0 - result, result]).T
        return result

    @property
    def classes_(self):
        return self._classes

    @property
    def n_classes_(self) -> int:
        return self._n_classes


class LGBMRanker(LGBMModel):
    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if self._objective is None:
            self._objective = "lambdarank"

    def fit(self, X, y, group=None, eval_group=None, eval_at=(1,),
            **kwargs) -> "LGBMRanker":
        if group is None:
            raise ValueError("Should set group for ranking task")
        if kwargs.get("eval_set") is not None and eval_group is None:
            raise ValueError("Eval_group cannot be None when eval_set is "
                             "not None")
        self._other_params["eval_at"] = list(eval_at)
        super().fit(X, y, group=group, eval_group=eval_group, **kwargs)
        return self
