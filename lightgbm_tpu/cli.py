"""Command-line application: ``python -m lightgbm_tpu config=train.conf``.

Re-creates the reference CLI (`src/main.cpp`, `src/application/
application.cpp`): ``key=value`` args with a ``config=`` file
(`LoadParameters` `application.cpp:48-81`), task dispatch
train/predict/convert_model/refit (`application.h:78-88`), periodic
snapshots (`gbdt.cpp:289-293`), and prediction-result files compatible with
`Predictor` output (`src/application/predictor.hpp`).

The reference `examples/*/train.conf` files run unchanged. Where the
reference rendezvouses a TCP/MPI network for ``num_machines > 1``
(`application.cpp:166-200`), this build shards rows over the local
`jax.sharding.Mesh` — multi-host execution uses JAX distributed
initialization instead of a machine list file.
"""
from __future__ import annotations

import os
import sys
from typing import Dict, List, Optional

import numpy as np

from .basic import Booster, Dataset, LightGBMError
from .config import Config
from .engine import train as engine_train
from .io.loader import DatasetLoader


def parse_cli_args(argv: List[str]) -> Dict[str, str]:
    """``key=value`` tokens; ``config=file`` pulls in a config file whose
    entries CLI args override (reference `Application::LoadParameters`)."""
    cli: Dict[str, str] = {}
    for tok in argv:
        tok = tok.strip()
        if not tok or tok.startswith("#"):
            continue
        if "=" not in tok:
            raise LightGBMError(f"Unknown CLI argument: {tok!r}")
        k, v = tok.split("=", 1)
        cli[k.strip()] = v.strip()
    params: Dict[str, str] = {}
    conf_file = cli.get("config", cli.get("config_file", ""))
    if conf_file:
        params.update(read_config_file(conf_file))
    params.update(cli)  # CLI wins over config file
    params.pop("config", None)
    params.pop("config_file", None)
    return params


def read_config_file(path: str) -> Dict[str, str]:
    """``key = value`` lines, ``#`` comments (reference `Config::KV2Map`)."""
    if not os.path.isfile(path):
        raise LightGBMError(f"Config file {path} doesn't exist")
    out: Dict[str, str] = {}
    with open(path) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line or "=" not in line:
                continue
            k, v = line.split("=", 1)
            out[k.strip()] = v.strip()
    return out


def _wrap_core(core, params) -> Dataset:
    d = Dataset(None, params=dict(params))
    d._handle = core
    d.free_raw_data = False
    return d


class Application:
    """reference `Application` (`include/LightGBM/application.h:35-92`)."""

    def __init__(self, argv: List[str]) -> None:
        self.raw_params = parse_cli_args(argv)
        self.config = Config.from_params(self.raw_params)
        if self.config.num_threads > 0:
            os.environ.setdefault("OMP_NUM_THREADS",
                                  str(self.config.num_threads))

    # ------------------------------------------------------------------
    def run(self) -> int:
        task = self.config.task
        if task == "train":
            return self.train()
        elif task in ("predict", "prediction", "test"):
            self.predict()
        elif task in ("convert_model",):
            self.convert_model()
        elif task == "refit":
            self.refit()
        elif task == "serve":
            return self.serve()
        else:
            raise LightGBMError(f"Unknown task type {task}")
        return 0

    # ------------------------------------------------------------------
    def _load_train_data(self):
        cfg = self.config
        if not cfg.data:
            raise LightGBMError("No training data: set data=<file>")
        predict_fun = None
        if cfg.input_model and os.path.isfile(cfg.input_model):
            # continued training: prior model's raw predictions become the
            # init score (reference application.cpp:90-93)
            prior = Booster(model_file=cfg.input_model)
            predict_fun = lambda X: prior.predict(X, raw_score=True)  # noqa: E731
        loader = DatasetLoader(cfg, predict_fun=predict_fun)
        core = loader.load_from_file(cfg.data)
        ing = getattr(core, "_ingest_stats", None)
        if ing:
            print(f"Streamed ingest: {ing['rows']} rows in chunks of "
                  f"{ing['chunk_rows']} ({ing['device_cols']} "
                  f"device-binned + {ing['host_cols']} host-binned "
                  f"columns, "
                  f"{getattr(core, '_ingest_ms', 0.0) / 1e3:.1f} s)")
        train_set = _wrap_core(core, self.raw_params)
        valid_sets, valid_names = [], []
        for vf in cfg.valid:
            vcore = loader.load_from_file_align_with_other_dataset(vf, core)
            valid_sets.append(_wrap_core(vcore, self.raw_params))
            valid_names.append(os.path.basename(vf))
        return train_set, valid_sets, valid_names

    def train(self) -> int:
        cfg = self.config
        if cfg.tpu_trace:
            # enable the file-backed tracer BEFORE data load: ingest
            # fires its events (stream_ingest / dist_stream / dist_init)
            # during dataset construction, and the timeline's events tee
            # only captures what happens after the trace dir exists
            # (GBDT.__init__'s own enable() call is an idempotent no-op)
            from .obs import trace as obs_trace
            obs_trace.enable(cfg.tpu_trace_dir or "lgbt_trace")
        train_set, valid_sets, valid_names = self._load_train_data()
        if cfg.is_provide_training_metric:
            valid_sets = [train_set] + valid_sets
            valid_names = ["training"] + valid_names
        callbacks = []
        if cfg.snapshot_freq > 0 and cfg.output_model \
                and not cfg.tpu_checkpoint_dir:
            # legacy model-only snapshots; with tpu_checkpoint_dir the
            # engine writes full-state checkpoints instead
            callbacks.append(_snapshot_callback(cfg.output_model,
                                                cfg.snapshot_freq,
                                                cfg.tpu_snapshot_keep))
        if cfg.tpu_trace:
            # CLI traced runs re-emit each round record on the
            # structured channel at metric frequency (snapshot-style:
            # progress is observable mid-run, not only at the end)
            from .callback import log_telemetry
            callbacks.append(log_telemetry(period=max(1, cfg.metric_freq)))
        booster = engine_train(
            dict(self.raw_params), train_set,
            num_boost_round=cfg.num_iterations,
            valid_sets=valid_sets, valid_names=valid_names,
            init_model=(cfg.input_model or None),
            verbose_eval=max(1, cfg.metric_freq),
            callbacks=callbacks)
        out = cfg.output_model or "LightGBM_model.txt"
        booster.save_model(out)
        if cfg.tpu_trace:
            from . import compile_cache
            from .obs import trace as obs_trace
            tdir = cfg.tpu_trace_dir or "lgbt_trace"
            # fold the compile-cache story in next to the spans: total
            # persistent-cache hits/misses, which attributed program
            # each miss blamed, and the process trace count — the
            # warm-up forensics that used to need a bench run
            extra = {"compile_cache": {
                **compile_cache.persistent_cache_events(),
                "miss_by_program": compile_cache.miss_attribution(),
                "traces": compile_cache.trace_count(),
                "cache_dir": compile_cache.persistent_cache_dir(),
            }}
            # in-run profiler (tpu_profile): sampled rounds, last
            # terms_ms, build calibration, the program_costs.json path
            # (written here), and any jax.profiler capture artifacts
            prof = getattr(booster, "profiler", None)
            if prof is not None:
                extra["profiler"] = prof.summary(tdir)
            dump = obs_trace.write(
                os.path.join(tdir, "trace_summary.json"), extra=extra)
            print(f"Telemetry: span summary at {dump}")
            from .obs import timeline as obs_timeline
            if obs_timeline.timeline_on(cfg):
                tl = obs_timeline.build_timeline(tdir)
                tpath = obs_timeline.write_timeline(
                    os.path.join(tdir, "timeline.json"), tl)
                print(f"Telemetry: run timeline at {tpath} "
                      f"(open in Perfetto / chrome://tracing)")
        if getattr(booster, "_preempted", False):
            from .resilience import EXIT_PREEMPTED
            print(f"Preempted mid-training; checkpoint flushed. "
                  f"Partial model saved to {out} — rerun the same "
                  f"command to resume.")
            return EXIT_PREEMPTED
        print(f"Finished training. Model saved to {out}")
        return 0

    # ------------------------------------------------------------------
    def predict(self) -> None:
        cfg = self.config
        if not cfg.input_model:
            raise LightGBMError("No model file: set input_model=<file>")
        if not cfg.data:
            raise LightGBMError("No prediction data: set data=<file>")
        booster = Booster(params=dict(self.raw_params),
                          model_file=cfg.input_model)
        num_iteration = cfg.num_iteration_predict
        # hand the PATH to Booster.predict: its file branch carries the
        # reference's label-free detection (a file whose column count
        # equals the model's feature count has no label column to strip,
        # predictor.hpp:185) which a direct DatasetLoader.parse_file
        # call would skip, silently shifting every feature by one
        preds = booster.predict(
            cfg.data,
            num_iteration=(num_iteration if num_iteration > 0 else None),
            raw_score=cfg.predict_raw_score,
            pred_leaf=cfg.predict_leaf_index,
            pred_contrib=cfg.predict_contrib,
            start_iteration=cfg.start_iteration_predict,
            tpu_predict_device=cfg.tpu_predict_device)
        out = cfg.output_result or "LightGBM_predict_result.txt"
        arr = np.atleast_1d(np.asarray(preds))
        from .io.file_io import open_file
        with open_file(out, "w") as f:
            if arr.ndim == 1:
                for v in arr:
                    f.write(f"{v:g}\n")
            else:
                for row in arr:
                    f.write("\t".join(f"{v:g}" for v in row) + "\n")
        print(f"Finished prediction. Results saved to {out}")

    # ------------------------------------------------------------------
    def convert_model(self) -> None:
        cfg = self.config
        if not cfg.input_model:
            raise LightGBMError("No model file: set input_model=<file>")
        from .models.model_text import model_to_if_else
        booster = Booster(model_file=cfg.input_model)
        out = cfg.convert_model or "gbdt_prediction.cpp"
        code = model_to_if_else(booster.trees,
                                booster.num_tree_per_iteration,
                                average_output=booster._is_average_output())
        from .io.file_io import open_file
        with open_file(out, "w") as f:
            f.write(code)
        print(f"Finished converting model. Code saved to {out}")

    # ------------------------------------------------------------------
    def serve(self) -> int:
        """Batch-mode driver for the serving service (serving/): load the
        named models (``input_model=name=file[,name2=file2]``; a bare
        path serves under its basename) and/or watch a checkpoint
        directory (``tpu_checkpoint_dir=`` — hot-swaps while running),
        then score ``data=`` through the request coalescer into
        ``output_result``. Scores are RAW margins (the service
        contract), i.e. what ``task=predict predict_raw_score=true``
        writes. With no data file the models are loaded, stats print,
        and the process exits — a smoke/validation mode."""
        import json
        cfg = self.config
        from .serving import ServingService
        if not cfg.input_model and not cfg.tpu_checkpoint_dir:
            raise LightGBMError(
                "task=serve needs input_model=<[name=]file,...> and/or "
                "tpu_checkpoint_dir=<dir>")
        svc = ServingService(params=dict(self.raw_params))
        try:
            names: List[str] = []
            if cfg.input_model:
                for i, spec in enumerate(
                        s.strip() for s in cfg.input_model.split(",")
                        if s.strip()):
                    if "=" in spec:
                        name, path = (t.strip()
                                      for t in spec.split("=", 1))
                    else:
                        path = spec
                        name = os.path.splitext(
                            os.path.basename(spec))[0] or f"model{i}"
                    svc.load_model(name, model_file=path)
                    names.append(name)
            if cfg.tpu_checkpoint_dir:
                svc.watch("checkpoint", cfg.tpu_checkpoint_dir)
                if svc.registry.get("checkpoint") is None:
                    raise LightGBMError(
                        f"no readable checkpoint manifest under "
                        f"{cfg.tpu_checkpoint_dir}")
                names.append("checkpoint")
            if cfg.data:
                loader = DatasetLoader(cfg)
                _labels, feats, _ex = loader.parse_file(cfg.data)
                target = names[0]
                req_rows = max(min(cfg.tpu_serve_max_batch_rows, 1024), 1)
                futs = [svc.predict_async(target, feats[s:s + req_rows])
                        for s in range(0, len(feats), req_rows)]
                preds = np.concatenate([np.atleast_1d(f.result(timeout=600))
                                        for f in futs], axis=0)
                out = cfg.output_result or "LightGBM_predict_result.txt"
                from .io.file_io import open_file
                with open_file(out, "w") as f:
                    if preds.ndim == 1:
                        for v in preds:
                            f.write(f"{v:g}\n")
                    else:
                        for row in preds:
                            f.write("\t".join(f"{v:g}" for v in row) + "\n")
                print(f"Finished serving {len(preds)} rows on "
                      f"{target!r}. Results saved to {out}")
            print("Serving stats: "
                  + json.dumps(svc.stats(), sort_keys=True, default=str))
            ac = svc.registry.aot_compact_stats()
            if any(m["aot"]["buckets"] or m["compact"]["plan"] != "off"
                   for m in ac.values()):
                print("Serving aot/compact: "
                      + json.dumps(ac, sort_keys=True, default=str))
            if svc.exporter is not None:
                print(f"Metrics: {svc.exporter.url}/metrics "
                      f"(Prometheus) and /metrics.json", flush=True)
                if svc.tracer is not None:
                    print(f"Request traces: {svc.exporter.url}"
                          f"/debug/requests", flush=True)
            if svc.frontend is not None:
                print(f"Scoring: POST {svc.frontend.url}/v1/score/"
                      f"<model> (health: {svc.frontend.url}/healthz)",
                      flush=True)
            if cfg.tpu_serve_hold_s > 0:
                # scrape/hot-swap window: hold the service up, exit
                # early and cleanly on Ctrl-C / SIGTERM
                import time as _time
                print(f"Holding for {cfg.tpu_serve_hold_s:g}s "
                      f"(tpu_serve_hold_s)...", flush=True)
                try:
                    _time.sleep(cfg.tpu_serve_hold_s)
                except KeyboardInterrupt:
                    pass
        finally:
            svc.close()
        return 0

    # ------------------------------------------------------------------
    def refit(self) -> None:
        cfg = self.config
        if not cfg.input_model:
            raise LightGBMError("No model file: set input_model=<file>")
        if not cfg.data:
            raise LightGBMError("No refit data: set data=<file>")
        booster = Booster(model_file=cfg.input_model,
                          params=dict(self.raw_params))
        loader = DatasetLoader(cfg)
        labels, feats, _ex = loader.parse_file(cfg.data)
        leaf_preds = booster.predict(feats, pred_leaf=True)
        booster.refit(feats, labels, decay_rate=cfg.refit_decay_rate,
                      leaf_preds=leaf_preds)
        out = cfg.output_model or "LightGBM_model.txt"
        booster.save_model(out)
        print(f"Finished refitting. Model saved to {out}")


def _snapshot_callback(output_model: str, freq: int, keep: int = 3):
    """Periodic model snapshots (reference gbdt.cpp:289-293), written
    atomically (tmp + rename — a kill mid-write never leaves a torn
    snapshot) with rolling retention of the newest `keep` files."""
    from .resilience import atomic_write_text, prune_snapshots

    def _cb(env):
        it = env.iteration + 1
        if it % freq == 0:
            atomic_write_text(f"{output_model}.snapshot_iter_{it}",
                              env.model.model_to_string())
            prune_snapshots(output_model, keep)
    _cb.order = 100
    return _cb


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv:
        print("Usage: python -m lightgbm_tpu config=train.conf [key=value ...]")
        return 1
    try:
        rc = Application(argv).run()
    except LightGBMError as e:
        print(f"[LightGBM-TPU] [Fatal] {e}", file=sys.stderr)
        return 1
    return int(rc or 0)


if __name__ == "__main__":
    sys.exit(main())
