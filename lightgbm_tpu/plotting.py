"""Plotting utilities (reference `python-package/lightgbm/plotting.py`).

Same public surface: `plot_importance`, `plot_split_value_histogram`,
`plot_metric`, `plot_tree`, `create_tree_digraph`. matplotlib / graphviz are
imported lazily so the core package has no hard dependency on them
(reference gates the same way via compat flags, plotting.py:10-22).
"""
from __future__ import annotations

from copy import deepcopy
from io import BytesIO
from typing import Any, Dict, List, Optional, Tuple, Union

import numpy as np

from .basic import Booster, LightGBMError
from .sklearn import LGBMModel


def _check_not_tuple_of_2_elements(obj, obj_name: str) -> None:
    if not isinstance(obj, (list, tuple)) or len(obj) != 2:
        raise TypeError(f"{obj_name} must be a list/tuple of 2 elements")


def _to_booster(booster) -> Booster:
    if isinstance(booster, LGBMModel):
        return booster.booster_
    if isinstance(booster, Booster):
        return booster
    raise TypeError("booster must be Booster or LGBMModel")


def plot_importance(booster, ax=None, height: float = 0.2,
                    xlim: Optional[Tuple] = None,
                    ylim: Optional[Tuple] = None,
                    title: str = "Feature importance",
                    xlabel: str = "Feature importance",
                    ylabel: str = "Features",
                    importance_type: str = "split",
                    max_num_features: Optional[int] = None,
                    ignore_zero: bool = True, figsize=None, grid: bool = True,
                    precision: Optional[int] = 3, **kwargs):
    """Bar chart of feature importances (reference plotting.py:24-126)."""
    import matplotlib.pyplot as plt

    booster = _to_booster(booster)
    importance = booster.feature_importance(importance_type=importance_type)
    feature_name = booster.feature_name()
    if not len(importance):
        raise ValueError("Booster's feature_importance is empty")

    tuples = sorted(zip(feature_name, importance), key=lambda x: x[1])
    if ignore_zero:
        tuples = [x for x in tuples if x[1] > 0]
    if max_num_features is not None and max_num_features > 0:
        tuples = tuples[-max_num_features:]
    if not tuples:
        raise ValueError("No features with non-zero importance")
    labels, values = zip(*tuples)

    if ax is None:
        if figsize is not None:
            _check_not_tuple_of_2_elements(figsize, "figsize")
        _, ax = plt.subplots(1, 1, figsize=figsize)
    ylocs = np.arange(len(values))
    ax.barh(ylocs, values, align="center", height=height, **kwargs)
    for x, y in zip(values, ylocs):
        val = round(x, precision) if precision is not None else x
        ax.text(x + 1, y, str(val), va="center")
    ax.set_yticks(ylocs)
    ax.set_yticklabels(labels)
    if xlim is not None:
        _check_not_tuple_of_2_elements(xlim, "xlim")
    else:
        xlim = (0, max(values) * 1.1)
    ax.set_xlim(xlim)
    if ylim is not None:
        _check_not_tuple_of_2_elements(ylim, "ylim")
    else:
        ylim = (-1, len(values))
    ax.set_ylim(ylim)
    if title is not None:
        ax.set_title(title)
    if xlabel is not None:
        ax.set_xlabel(xlabel)
    if ylabel is not None:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def plot_split_value_histogram(booster, feature, bins=None, ax=None,
                               width_coef: float = 0.8,
                               xlim: Optional[Tuple] = None,
                               ylim: Optional[Tuple] = None,
                               title: Optional[str] = "Split value histogram "
                               "for feature with @index/name@ @feature@",
                               xlabel: Optional[str] = "Feature split value",
                               ylabel: Optional[str] = "Count",
                               figsize=None, grid: bool = True, **kwargs):
    """Histogram of a feature's split values
    (reference plotting.py:129-225)."""
    import matplotlib.pyplot as plt
    from matplotlib.ticker import MaxNLocator

    booster = _to_booster(booster)
    hist, bin_edges = booster.get_split_value_histogram(
        feature=feature, bins=bins, xgboost_style=False)
    if np.count_nonzero(hist) == 0:
        raise ValueError(f"Cannot plot split value histogram, "
                         f"because feature {feature} was not used in "
                         f"splitting")
    width = width_coef * (bin_edges[1] - bin_edges[0])
    centred = (bin_edges[:-1] + bin_edges[1:]) / 2

    if ax is None:
        if figsize is not None:
            _check_not_tuple_of_2_elements(figsize, "figsize")
        _, ax = plt.subplots(1, 1, figsize=figsize)
    ax.bar(centred, hist, align="center", width=width, **kwargs)
    if xlim is not None:
        _check_not_tuple_of_2_elements(xlim, "xlim")
    else:
        range_result = bin_edges[-1] - bin_edges[0]
        xlim = (bin_edges[0] - range_result * 0.2,
                bin_edges[-1] + range_result * 0.2)
    ax.set_xlim(xlim)
    ax.yaxis.set_major_locator(MaxNLocator(integer=True))
    if ylim is not None:
        _check_not_tuple_of_2_elements(ylim, "ylim")
    else:
        ylim = (0, max(hist) * 1.1)
    ax.set_ylim(ylim)
    if title is not None:
        title = title.replace("@feature@", str(feature))
        title = title.replace("@index/name@",
                              "name" if isinstance(feature, str) else "index")
        ax.set_title(title)
    if xlabel is not None:
        ax.set_xlabel(xlabel)
    if ylabel is not None:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def plot_metric(booster: Union[Dict, "LGBMModel"], metric: Optional[str] = None,
                dataset_names: Optional[List[str]] = None, ax=None,
                xlim: Optional[Tuple] = None, ylim: Optional[Tuple] = None,
                title: Optional[str] = "Metric during training",
                xlabel: Optional[str] = "Iterations",
                ylabel: Optional[str] = "auto", figsize=None,
                grid: bool = True):
    """Plot a metric recorded by `record_evaluation`
    (reference plotting.py:228-331)."""
    import matplotlib.pyplot as plt

    if isinstance(booster, LGBMModel):
        eval_results = deepcopy(booster.evals_result_)
    elif isinstance(booster, dict):
        eval_results = deepcopy(booster)
    else:
        raise TypeError("booster must be dict or LGBMModel")
    num_data = len(eval_results)
    if not num_data:
        raise ValueError("eval results cannot be empty")

    if ax is None:
        if figsize is not None:
            _check_not_tuple_of_2_elements(figsize, "figsize")
        _, ax = plt.subplots(1, 1, figsize=figsize)

    if dataset_names is None:
        dataset_names_iter = iter(eval_results.keys())
    elif not isinstance(dataset_names, (list, tuple, set)) \
            or not dataset_names:
        raise ValueError("dataset_names should be iterable and cannot be "
                         "empty")
    else:
        dataset_names_iter = iter(dataset_names)

    name = next(dataset_names_iter)
    metrics_for_one = eval_results[name]
    num_metric = len(metrics_for_one)
    if metric is None:
        if num_metric > 1:
            raise ValueError("more than one metric available, pick one with "
                             "the metric parameter")
        metric, results = metrics_for_one.popitem()
    else:
        if metric not in metrics_for_one:
            raise ValueError("No given metric in eval results")
        results = metrics_for_one[metric]
    num_iteration = len(results)
    max_result, min_result = max(results), min(results)
    x_ = range(num_iteration)
    ax.plot(x_, results, label=name)

    for name in dataset_names_iter:
        metrics_for_one = eval_results[name]
        results = metrics_for_one[metric]
        max_result = max(max(results), max_result)
        min_result = min(min(results), min_result)
        ax.plot(x_, results, label=name)

    ax.legend(loc="best")
    if xlim is not None:
        _check_not_tuple_of_2_elements(xlim, "xlim")
    else:
        xlim = (0, num_iteration)
    ax.set_xlim(xlim)
    if ylim is not None:
        _check_not_tuple_of_2_elements(ylim, "ylim")
    else:
        range_result = max_result - min_result
        ylim = (min_result - range_result * 0.2,
                max_result + range_result * 0.2)
    ax.set_ylim(ylim)
    if ylabel == "auto":
        ylabel = metric
    if title is not None:
        ax.set_title(title)
    if xlabel is not None:
        ax.set_xlabel(xlabel)
    if ylabel is not None:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def _float2str(value, precision: Optional[int] = None) -> str:
    if precision is not None and not isinstance(value, str):
        return f"{value:.{precision}f}"
    return str(value)


def create_tree_digraph(booster, tree_index: int = 0,
                        show_info: Optional[List[str]] = None,
                        precision: Optional[int] = 3,
                        orientation: str = "horizontal",
                        **kwargs):
    """Graphviz digraph of one tree (reference plotting.py:402-473)."""
    import graphviz

    booster = _to_booster(booster)
    model = booster.dump_model()
    tree_infos = model["tree_info"]
    feature_names = model.get("feature_names", None)
    if tree_index >= len(tree_infos):
        raise IndexError("tree_index is out of range")
    tree_info = tree_infos[tree_index]
    show_info = show_info or []

    graph = graphviz.Digraph(**kwargs)
    rankdir = "LR" if orientation == "horizontal" else "TB"
    graph.attr(rankdir=rankdir)

    def add(node: Dict[str, Any], parent: Optional[str] = None,
            decision: Optional[str] = None) -> None:
        if "split_index" in node:
            name = f"split{node['split_index']}"
            if feature_names is not None:
                label = (f"<B>{feature_names[node['split_feature']]}</B>")
            else:
                label = f"feature <B>{node['split_feature']}</B>"
            direction = "&#8804;" if node["decision_type"] == "<=" else "="
            label = (f"<{label} {direction} "
                     f"<B>{_float2str(node['threshold'], precision)}</B>")
            for info in ("split_gain", "internal_value", "internal_count"):
                if info in show_info and info in node:
                    label += (f"<br/>{info.split('_')[-1]}: "
                              f"{_float2str(node[info], precision)}")
            label += ">"
            graph.node(name, label=label)
            add(node["left_child"], name,
                "yes" if node["default_left"] else "no")
            add(node["right_child"], name,
                "no" if node["default_left"] else "yes")
        else:
            name = f"leaf{node['leaf_index']}"
            label = (f"leaf {node['leaf_index']}: "
                     f"{_float2str(node['leaf_value'], precision)}")
            if "leaf_count" in show_info and "leaf_count" in node:
                label += f"\ncount: {node['leaf_count']}"
            graph.node(name, label=label)
        if parent is not None:
            graph.edge(parent, name, decision)

    if "tree_structure" in tree_info:
        add(tree_info["tree_structure"])
    return graph


def plot_tree(booster, ax=None, tree_index: int = 0, figsize=None,
              show_info: Optional[List[str]] = None,
              precision: Optional[int] = 3,
              orientation: str = "horizontal", **kwargs):
    """Render one tree with matplotlib via graphviz
    (reference plotting.py:476-560)."""
    import matplotlib.image as mpimg
    import matplotlib.pyplot as plt

    if ax is None:
        if figsize is not None:
            _check_not_tuple_of_2_elements(figsize, "figsize")
        _, ax = plt.subplots(1, 1, figsize=figsize)
    graph = create_tree_digraph(booster, tree_index=tree_index,
                                show_info=show_info, precision=precision,
                                orientation=orientation, **kwargs)
    try:
        s = BytesIO(graph.pipe(format="png"))
    except Exception as e:  # graphviz binary missing
        raise LightGBMError(f"graphviz rendering failed: {e}")
    img = mpimg.imread(s)
    ax.imshow(img)
    ax.axis("off")
    return ax
