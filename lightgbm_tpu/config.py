"""Typed configuration for the TPU GBDT framework.

Re-creates the parameter surface of the reference `struct Config`
(`include/LightGBM/config.h:31+`, parsing in `src/io/config.cpp:15-283`,
alias table generated into `src/io/config_auto.cpp`): a single flat config with
key=value parsing, alias expansion, and conflict checks, so that reference
`train.conf` files and `lgb.train(params={...})` dicts work unchanged.

TPU-specific additions are grouped at the bottom (histogram precision,
pallas toggle, mesh axes) — the analogue of the reference's `gpu_*` block
(`config.h:818-826`).
"""
from __future__ import annotations

import dataclasses
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

# ---------------------------------------------------------------------------
# Alias table: maps every accepted alias to the canonical parameter name.
# Mirrors the generated table in the reference `src/io/config_auto.cpp`
# (source comments `include/LightGBM/config.h`, e.g. `alias = ...` lines).
# ---------------------------------------------------------------------------
_ALIASES: Dict[str, str] = {
    "config_file": "config",
    "task_type": "task",
    "objective_type": "objective", "app": "objective", "application": "objective",
    "boosting_type": "boosting", "boost": "boosting",
    "train": "data", "train_data": "data", "train_data_file": "data",
    "data_filename": "data",
    "test": "valid", "valid_data": "valid", "valid_data_file": "valid",
    "test_data": "valid", "test_data_file": "valid", "valid_filenames": "valid",
    "num_iteration": "num_iterations", "n_iter": "num_iterations",
    "num_tree": "num_iterations", "num_trees": "num_iterations",
    "num_round": "num_iterations", "num_rounds": "num_iterations",
    "num_boost_round": "num_iterations", "n_estimators": "num_iterations",
    "shrinkage_rate": "learning_rate", "eta": "learning_rate",
    "num_leaf": "num_leaves", "max_leaves": "num_leaves", "max_leaf": "num_leaves",
    "tree": "tree_learner", "tree_type": "tree_learner",
    "tree_learner_type": "tree_learner",
    "num_thread": "num_threads", "nthread": "num_threads",
    "nthreads": "num_threads", "n_jobs": "num_threads",
    "device": "device_type",
    "random_seed": "seed", "random_state": "seed",
    "min_data_per_leaf": "min_data_in_leaf", "min_data": "min_data_in_leaf",
    "min_child_samples": "min_data_in_leaf",
    "min_sum_hessian_per_leaf": "min_sum_hessian_in_leaf",
    "min_sum_hessian": "min_sum_hessian_in_leaf",
    "min_hessian": "min_sum_hessian_in_leaf",
    "min_child_weight": "min_sum_hessian_in_leaf",
    "sub_row": "bagging_fraction", "subsample": "bagging_fraction",
    "bagging": "bagging_fraction",
    "pos_sub_row": "pos_bagging_fraction", "pos_subsample": "pos_bagging_fraction",
    "pos_bagging": "pos_bagging_fraction",
    "neg_sub_row": "neg_bagging_fraction", "neg_subsample": "neg_bagging_fraction",
    "neg_bagging": "neg_bagging_fraction",
    "subsample_freq": "bagging_freq",
    "bagging_fraction_seed": "bagging_seed",
    "sub_feature": "feature_fraction", "colsample_bytree": "feature_fraction",
    "early_stopping_rounds": "early_stopping_round",
    "early_stopping": "early_stopping_round",
    "max_tree_output": "max_delta_step", "max_leaf_output": "max_delta_step",
    "reg_alpha": "lambda_l1",
    "reg_lambda": "lambda_l2", "lambda": "lambda_l2",
    "min_split_gain": "min_gain_to_split",
    "rate_drop": "drop_rate",
    "topk": "top_k",
    "mc": "monotone_constraints", "monotone_constraint": "monotone_constraints",
    "feature_contrib": "feature_contri", "fc": "feature_contri",
    "fp": "feature_contri", "feature_penalty": "feature_contri",
    "fs": "forcedsplits_filename", "forced_splits_filename": "forcedsplits_filename",
    "forced_splits_file": "forcedsplits_filename",
    "forced_splits": "forcedsplits_filename",
    "verbose": "verbosity",
    "subsample_for_bin": "bin_construct_sample_cnt",
    "hist_pool_size": "histogram_pool_size",
    "data_seed": "data_random_seed",
    "model_output": "output_model", "model_out": "output_model",
    "save_period": "snapshot_freq",
    "model_input": "input_model", "model_in": "input_model",
    "predict_result": "output_result", "prediction_result": "output_result",
    "predict_name": "output_result", "prediction_name": "output_result",
    "pred_name": "output_result", "name_pred": "output_result",
    "init_score_filename": "initscore_filename",
    "init_score_file": "initscore_filename", "init_score": "initscore_filename",
    "input_init_score": "initscore_filename",
    "valid_data_init_scores": "valid_initscore_filenames",
    "valid_data_initscores": "valid_initscore_filenames",
    "valid_init_score_file": "valid_initscore_filenames",
    "valid_init_score": "valid_initscore_filenames",
    "is_pre_partition": "pre_partition",
    "is_enable_bundle": "enable_bundle", "bundle": "enable_bundle",
    "is_sparse": "is_enable_sparse", "enable_sparse": "is_enable_sparse",
    "sparse": "is_enable_sparse",
    "two_round_loading": "two_round", "use_two_round_loading": "two_round",
    "is_save_binary": "save_binary", "is_save_binary_file": "save_binary",
    "has_header": "header",
    "label": "label_column",
    "weight": "weight_column",
    "group": "group_column", "group_id": "group_column",
    "query_column": "group_column", "query": "group_column",
    "query_id": "group_column",
    "ignore_feature": "ignore_column", "blacklist": "ignore_column",
    "cat_feature": "categorical_feature",
    "categorical_column": "categorical_feature",
    "cat_column": "categorical_feature",
    "is_predict_raw_score": "predict_raw_score",
    "predict_rawscore": "predict_raw_score", "raw_score": "predict_raw_score",
    "is_predict_leaf_index": "predict_leaf_index",
    "leaf_index": "predict_leaf_index",
    "is_predict_contrib": "predict_contrib", "contrib": "predict_contrib",
    "convert_model_file": "convert_model",
    "num_classes": "num_class",
    "unbalance": "is_unbalance", "unbalanced_sets": "is_unbalance",
    "metrics": "metric", "metric_types": "metric",
    "output_freq": "metric_freq",
    "training_metric": "is_provide_training_metric",
    "is_training_metric": "is_provide_training_metric",
    "train_metric": "is_provide_training_metric",
    "ndcg_eval_at": "eval_at", "ndcg_at": "eval_at", "map_eval_at": "eval_at",
    "map_at": "eval_at",
    "num_machine": "num_machines",
    "local_port": "local_listen_port", "port": "local_listen_port",
    "machine_list_file": "machine_list_filename",
    "machine_list": "machine_list_filename", "mlist": "machine_list_filename",
    "workers": "machines", "nodes": "machines",
}

# objective-name aliases (reference `config.h:106-126` descl2 lines,
# normalization in `src/objective/objective_function.cpp` / ParseObjectiveAlias)
_OBJECTIVE_ALIASES: Dict[str, str] = {
    "regression": "regression", "regression_l2": "regression",
    "mean_squared_error": "regression", "mse": "regression",
    "l2": "regression", "l2_root": "regression",
    "root_mean_squared_error": "regression", "rmse": "regression",
    "regression_l1": "regression_l1", "l1": "regression_l1",
    "mean_absolute_error": "regression_l1", "mae": "regression_l1",
    "huber": "huber", "fair": "fair", "poisson": "poisson",
    "quantile": "quantile",
    "mape": "mape", "mean_absolute_percentage_error": "mape",
    "gamma": "gamma", "tweedie": "tweedie",
    "binary": "binary",
    "multiclass": "multiclass", "softmax": "multiclass",
    "multiclassova": "multiclassova", "multiclass_ova": "multiclassova",
    "ova": "multiclassova", "ovr": "multiclassova",
    "xentropy": "xentropy", "cross_entropy": "xentropy",
    "xentlambda": "xentlambda", "cross_entropy_lambda": "xentlambda",
    "lambdarank": "lambdarank",
    "none": "none", "null": "none", "custom": "none", "na": "none",
}

_METRIC_ALIASES: Dict[str, str] = {
    "l1": "l1", "mean_absolute_error": "l1", "mae": "l1", "regression_l1": "l1",
    "l2": "l2", "mean_squared_error": "l2", "mse": "l2", "regression_l2": "l2",
    "regression": "l2",
    "l2_root": "rmse", "root_mean_squared_error": "rmse", "rmse": "rmse",
    "quantile": "quantile", "mape": "mape",
    "mean_absolute_percentage_error": "mape",
    "huber": "huber", "fair": "fair", "poisson": "poisson",
    "gamma": "gamma", "gamma_deviance": "gamma_deviance", "tweedie": "tweedie",
    "ndcg": "ndcg", "lambdarank": "ndcg",
    "map": "map", "mean_average_precision": "map",
    "auc": "auc",
    "binary_logloss": "binary_logloss", "binary": "binary_logloss",
    "binary_error": "binary_error",
    "multi_logloss": "multi_logloss", "multiclass": "multi_logloss",
    "softmax": "multi_logloss", "multiclassova": "multi_logloss",
    "multiclass_ova": "multi_logloss", "ova": "multi_logloss",
    "ovr": "multi_logloss",
    "multi_error": "multi_error",
    "xentropy": "xentropy", "cross_entropy": "xentropy",
    "xentlambda": "xentlambda", "cross_entropy_lambda": "xentlambda",
    "kldiv": "kldiv", "kullback_leibler": "kldiv",
    "none": "none", "na": "none", "null": "none", "custom": "none",
}

_TREE_LEARNER_ALIASES: Dict[str, str] = {
    "serial": "serial",
    "feature": "feature", "feature_parallel": "feature",
    "data": "data", "data_parallel": "data",
    "voting": "voting", "voting_parallel": "voting",
}

_BOOSTING_ALIASES: Dict[str, str] = {
    "gbdt": "gbdt", "gbrt": "gbdt",
    "dart": "dart",
    "goss": "goss",
    "rf": "rf", "random_forest": "rf",
}

_DEVICE_ALIASES: Dict[str, str] = {
    "cpu": "cpu", "gpu": "tpu", "tpu": "tpu",
}


def _kv_list(value: Any, typ) -> list:
    """Parse 'a,b,c' strings / sequences into a typed list."""
    if value is None or value == "":
        return []
    if isinstance(value, str):
        parts = [p for p in value.replace(" ", "").split(",") if p != ""]
        return [typ(p) for p in parts]
    if isinstance(value, (list, tuple)):
        return [typ(v) for v in value]
    return [typ(value)]


def _to_bool(v: Any) -> bool:
    if isinstance(v, bool):
        return v
    if isinstance(v, str):
        return v.strip().lower() in ("true", "1", "yes", "+")
    return bool(v)


@dataclass
class Config:
    """All training/IO/prediction parameters (reference `config.h:31+`)."""

    # --- core (config.h:84-208)
    task: str = "train"
    objective: str = "regression"
    boosting: str = "gbdt"
    data: str = ""
    valid: List[str] = field(default_factory=list)
    num_iterations: int = 100
    learning_rate: float = 0.1
    num_leaves: int = 31
    tree_learner: str = "serial"
    num_threads: int = 0
    device_type: str = "tpu"
    seed: int = 0

    # --- learning control (config.h:210-435)
    max_depth: int = -1
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    bagging_fraction: float = 1.0
    pos_bagging_fraction: float = 1.0
    neg_bagging_fraction: float = 1.0
    bagging_freq: int = 0
    bagging_seed: int = 3
    feature_fraction: float = 1.0
    feature_fraction_seed: int = 2
    early_stopping_round: int = 0
    first_metric_only: bool = False
    max_delta_step: float = 0.0
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_gain_to_split: float = 0.0
    drop_rate: float = 0.1
    max_drop: int = 50
    skip_drop: float = 0.5
    xgboost_dart_mode: bool = False
    uniform_drop: bool = False
    drop_seed: int = 4
    top_rate: float = 0.2
    other_rate: float = 0.1
    min_data_per_group: int = 100
    max_cat_threshold: int = 32
    cat_l2: float = 10.0
    cat_smooth: float = 10.0
    max_cat_to_onehot: int = 4
    top_k: int = 20
    monotone_constraints: List[int] = field(default_factory=list)
    feature_contri: List[float] = field(default_factory=list)
    forcedsplits_filename: str = ""
    refit_decay_rate: float = 0.9
    cegb_tradeoff: float = 1.0
    cegb_penalty_split: float = 0.0
    cegb_penalty_feature_lazy: List[float] = field(default_factory=list)
    cegb_penalty_feature_coupled: List[float] = field(default_factory=list)
    verbosity: int = 1

    # --- IO / dataset (config.h:437-600)
    max_bin: int = 255
    min_data_in_bin: int = 3
    bin_construct_sample_cnt: int = 200000
    histogram_pool_size: float = -1.0
    data_random_seed: int = 1
    output_model: str = "LightGBM_model.txt"
    snapshot_freq: int = -1
    input_model: str = ""
    output_result: str = "LightGBM_predict_result.txt"
    initscore_filename: str = ""
    valid_initscore_filenames: List[str] = field(default_factory=list)
    pre_partition: bool = False
    enable_bundle: bool = True
    max_conflict_rate: float = 0.0
    is_enable_sparse: bool = True
    sparse_threshold: float = 0.8
    use_missing: bool = True
    zero_as_missing: bool = False
    two_round: bool = False
    save_binary: bool = False
    header: bool = False
    label_column: str = ""
    weight_column: str = ""
    group_column: str = ""
    ignore_column: str = ""
    categorical_feature: str = ""

    # --- prediction (config.h:602-648)
    predict_raw_score: bool = False
    predict_leaf_index: bool = False
    predict_contrib: bool = False
    num_iteration_predict: int = -1
    start_iteration_predict: int = 0
    pred_early_stop: bool = False
    pred_early_stop_freq: int = 10
    pred_early_stop_margin: float = 10.0
    convert_model_language: str = ""
    convert_model: str = "gbdt_prediction.cpp"

    # --- objective (config.h:650-722)
    num_class: int = 1
    is_unbalance: bool = False
    scale_pos_weight: float = 1.0
    sigmoid: float = 1.0
    boost_from_average: bool = True
    reg_sqrt: bool = False
    alpha: float = 0.9
    fair_c: float = 1.0
    poisson_max_delta_step: float = 0.7
    tweedie_variance_power: float = 1.5
    max_position: int = 20
    label_gain: List[float] = field(default_factory=list)

    # --- metric (config.h:724-780)
    metric: List[str] = field(default_factory=list)
    metric_freq: int = 1
    is_provide_training_metric: bool = False
    eval_at: List[int] = field(default_factory=lambda: [1, 2, 3, 4, 5])
    multi_error_top_k: int = 1

    # --- network (config.h:782-809)
    num_machines: int = 1
    local_listen_port: int = 12400
    time_out: int = 120
    machine_list_filename: str = ""
    machines: str = ""

    # --- device: TPU block (replaces gpu_platform_id/gpu_device_id/gpu_use_dp,
    #     config.h:811-826)
    gpu_platform_id: int = -1
    gpu_device_id: int = -1
    gpu_use_dp: bool = False
    # accumulate histograms and root grad/hess sums in f64 (2x pass).
    # f64 sums of f32 gradients are exact at any realistic leaf size, so
    # the per-shard partials entering a cross-device all-reduce are
    # order-independent: a distributed run produces byte-identical model
    # text to a single-device run (the parity contract of the dist/
    # runtime — see docs/Distributed.md). Off by default: the bf16x2
    # MXU path is ~f32-accurate and faster on TPU
    tpu_use_f64_hist: bool = False
    # device count for the distributed runtime (dist/runtime.py): 0 =
    # derive from num_machines (>1) or use every visible device when a
    # non-serial tree_learner is selected; N > 0 = shard over exactly
    # the first N devices. Runtime-only topology: does not change the
    # trained model (see tpu_use_f64_hist) and is excluded from model
    # text and checkpoint signatures
    tpu_dist_devices: int = 0
    tpu_hist_chunk: int = 1 << 16        # rows per histogram matmul chunk
    # pallas VMEM-resident histogram kernel (ops/pallas_hist.py, the
    # ocl/histogram256.cl analogue): the one-hot tile never leaves VMEM,
    # vs the XLA einsum path whose chunk one-hots round-trip through HBM
    tpu_use_pallas: bool = True
    # trace gradients + tree build + score update as ONE program per
    # boosting iteration (saves per-program launch latency on tunneled
    # runtimes, but XLA compile time for the merged program is prohibitive
    # at large row counts — measured >15 min at 10.5M rows vs 132 s for
    # the split programs; enable only for small/medium datasets)
    tpu_fuse_iteration: bool = False
    # tree growth strategy. "leafwise" (default): the strictly sequential
    # reference order (serial_tree_learner.cpp:173-237) as one fused
    # whole-tree device program. "level"/"auto": the speculative
    # level-batched builder (models/level_builder.py) — exact leaf-wise
    # via host replay with automatic fallback — kept opt-in: on v5e its
    # per-round full-array passes (fills + record-carrying sort) measure
    # on par with the leaf-wise program, not faster
    # "aligned"/"auto": the chunk-aligned record pipeline
    # (models/aligned_builder.py + ops/aligned.py Pallas kernels) — exact
    # leaf-wise via host replay; measured ~4x faster per round than the
    # sort-based level builder on v5e. Auto picks aligned when its
    # restrictions hold (numerical features, pointwise single-class
    # objective; bagging IS supported) and a TPU is attached, else
    # leafwise.
    tpu_grow_mode: str = "auto"
    # speculation slots as a multiple of num_leaves for the level/aligned
    # builders; larger values let the exact leaf-wise replay absorb more
    # speculation churn before falling back. LATE-training iterations
    # speculate far more than early ones (gains converge and tie): a
    # full 500-iteration HIGGS-shape run at 3.0 fell back 106 times
    # after iteration ~100, while 4.5 measured ZERO fallbacks at both
    # 63 and 255 bins for ~5% per-iteration glue cost. Lowering this
    # trades that margin back for speed on short trainings.
    tpu_level_spec: float = 4.5
    tpu_min_pad: int = 1024              # smallest padded leaf size (compile cache)
    tpu_chunk: int = 0                   # aligned rows/chunk (0 = auto)
    # run the aligned pipeline's Pallas kernels in interpret mode (CPU
    # testing only — orders of magnitude slower than the TPU kernels)
    tpu_aligned_interpret: bool = False
    tpu_mesh_axis: str = "data"          # mesh axis name for row sharding
    # serving-engine policy for Booster.predict (serve/ForestEngine):
    # "on" always scores on device via the depth-synchronized stacked
    # forest; "off" keeps the host/native walk; "auto" prefers the engine
    # on accelerator backends and falls back to it on CPU only when the
    # native predictor is unavailable and the batch is large enough to
    # amortize a compile
    tpu_predict_device: str = "auto"
    # force the aligned builder's big-n physical layout (exact i32 count
    # pass + route-word repack, normally n > 2^24 only) at any row count
    # so the path is testable on small data (VERDICT r5 #7)
    tpu_force_big_n: bool = False
    # sub-binned histogram accumulation for bin widths above 128 (the
    # 255-bin hot path): the bin index splits into hi/lo 4-bit halves and
    # each (row, feature) costs two 16-wide one-hots plus ONE MXU
    # contraction into a [16, 128] sub-bin tile, folded to [bin, 3] once
    # per pass — replacing the 128-wide one-hot of the legacy nibble
    # form. "auto"/"on": use it wherever the factored form applies
    # (> 128 bins); "off": keep the nibble form. Applies to both the
    # aligned-pipeline kernels (ops/aligned.py) and the standalone
    # pallas histogram (ops/pallas_hist.py)
    tpu_hist_subbin: str = "auto"
    # segment-fused lambdarank gradient kernel (ops/pallas_rank.py): one
    # Pallas program streams query segments (CSR doc offsets packed into
    # fixed-size row tiles) through VMEM and computes rank positions,
    # sigmoid pair factors (bf16 compute, f32 accumulation), NDCG deltas
    # and per-doc lambda/hessian in place — the [Q, S, S] pair tensors of
    # the bucketed path never exist in HBM, and ONE compiled program
    # replaces the per-bucket-size program ladder. "auto": fused when a
    # TPU is attached, bucketed otherwise; "on": fused everywhere
    # (interpret-mode kernel on CPU — slow, tests/CI only); "off": the
    # bucketed pair-tensor path. Queries longer than tpu_rank_tile fall
    # back to the bucketed path per query; a kernel build failure falls
    # back wholesale (warned + logged as a rank_fused event)
    tpu_rank_fused: str = "auto"
    # docs per fused lambdarank tile (multiple of 128). Larger tiles
    # amortize grid overhead but pay more masked cross-query pair work
    # inside each subtile band; 512 fits MSLR's 40..200-doc queries with
    # low waste. Queries longer than this are handled by the bucketed
    # fallback path
    tpu_rank_tile: int = 512
    # quantize the fused kernel's sigmoid *input* to this many bins over
    # the reference table range [-50, 50] — the semantics of the
    # reference's quantized sigmoid lookup table (rank_objective.hpp:71,
    # 2/(1+exp(2*sigmoid*x)) tabulated at bin left edges). 0 = exact
    # sigmoid (default: on TPU the exp is cheaper than a gather, so the
    # LUT exists for reference-parity experiments, not speed)
    tpu_rank_sigmoid_bins: int = 0
    # VMEM budget (MB) for the aligned move pass's [K+1]-slot histogram
    # store. When the store fits, it stays VMEM-resident for the whole
    # pass (fastest); when it does not (wide-F x 255-bin shapes, e.g.
    # MSLR F=137), it is kept in HBM and streamed through a 2-deep VMEM
    # staging ring with double-buffered async DMA — the per-round split
    # cap K stays at 256 instead of shrinking, and shapes that formerly
    # faulted off the aligned path run aligned. Lower it to force the
    # spill ring (tests); raise it only on parts with more VMEM
    tpu_hist_spill_vmem_mb: float = 48.0
    # rows per chunk for the streaming out-of-core ingest (io/stream.py).
    # 0 (default) keeps today's paths: one-shot in-memory construction,
    # or the host-side two_round push-rows flow when two_round=true.
    # > 0 routes file loads AND in-memory matrix construction through
    # the chunked streaming pipeline: one bounded sample pass computes
    # bin boundaries (bitwise-equal to the single-host draw), then each
    # chunk is binned ON DEVICE by a jitted searchsorted kernel and
    # appended straight into the HBM-resident binned matrix — peak host
    # memory stays O(chunk_rows), so datasets larger than host RAM
    # train. The trained model is byte-equal to the in-memory path at
    # the same sampled boundaries (runtime-only: not part of the model)
    tpu_stream_chunk_rows: int = 0
    # stream-to-shard ingest (io/stream.py + dist/runtime.py): when a
    # streamed load (tpu_stream_chunk_rows > 0) feeds a data-parallel
    # run, each chunk is binned ON ITS OWNER DEVICE and written straight
    # into that device's shard slice — the [n, U] single-host binned
    # matrix never exists and peak host memory stays O(chunk_rows)
    # regardless of n. "auto" (default): shard the stream whenever the
    # distributed runtime would activate (tree_learner=data|voting and
    # a >1-wide mesh); "on": shard for data/voting even on a 1-wide
    # mesh (the host matrix is re-gathered on demand if a host-side
    # consumer needs it); "off": always assemble the host matrix and
    # shard later, today's two-step path. The sample draw is the same
    # canonical single-host draw either way, so the model stays
    # byte-equal at every mesh width (runtime-only: not part of the
    # model or the resume signature)
    tpu_stream_shard: str = "auto"
    # host->device staging depth of the streamed-ingest pipeline: with
    # the default 2, a producer thread parses chunk k+1 while chunk k
    # is being transferred/binned on device (two staging buffers +
    # async dispatch), so ingest wall-time approaches max(parse, bin)
    # instead of their sum. 0/1 disables the prefetch thread and runs
    # parse-then-bin sequentially (the honest baseline the bench's
    # overlap-efficiency number compares against; runtime-only)
    tpu_stream_pipeline_depth: int = 2
    # quantized gradient/hessian histogram accumulation on the MXU hist
    # path: per-tree stochastic-rounded int8/int16 gradient quantization
    # with per-leaf histogram rescale back to f32 units. Halves (int16)
    # or quarters (int8) the per-leaf grad/hess gather traffic — the
    # dominant HBM bandwidth term of the fused build program. "auto":
    # quantize when a TPU is attached and the fused leaf-wise path with
    # a bf16x2/pallas histogram runs; "on": quantize everywhere the
    # fused path can run (CPU included — tests/CI; the aligned engine is
    # gated off so the quantized fused path is actually exercised);
    # "off": today's f32 payload path, bitwise-unchanged — the parity
    # oracle, same fallback/oracle discipline as tpu_rank_fused. The
    # exact-f64 and gpu_use_dp histogram modes never quantize
    tpu_quant_hist: str = "auto"
    # quantized-histogram integer width: 16 (default) or 8. int16
    # payloads are exact under the bf16 hi/lo split (|q| <= 32767 needs
    # 15 mantissa bits); int8 (|q| <= 127) is exact in a SINGLE bf16
    # pass, so the hi/lo split collapses to one MXU issue — quarter the
    # gather bytes and half the matmul work, at more rounding noise per
    # tree (stochastic rounding keeps it unbiased)
    tpu_quant_hist_bits: int = 16
    # directory for jax's persistent XLA compilation cache (or via the
    # LGBT_COMPILE_CACHE_DIR environment variable). Wired BEFORE any
    # program traces, with the min-compile-time floor dropped to 0 s
    # (jax's default 2 s floor silently skips every sub-2 s round-loop
    # program) and the XLA-client caches enabled on non-TPU backends: a
    # fresh process loads compiled executables from disk instead of
    # recompiling, cutting warmup by the full XLA-compile bill. One-shot
    # per process — the first directory wins. In-process, training
    # programs are additionally deduplicated by a registry keyed on
    # shape/config/data fingerprints (compile_cache.py), so a second
    # Booster at the same shapes performs zero new traces either way
    tpu_compile_cache_dir: str = ""
    # first-class telemetry (obs/): per-round JSONL metrics ledger
    # (wall/device ms, new-trace count, training path, aligned vs
    # fallback rounds, gate notes, bagging sample sizes, eval values)
    # plus a host/device span tracer whose spans also land in
    # jax.profiler profiles. Off by default and FREE when off — the
    # round loop takes one attribute check and issues zero device
    # fences. On, each round is fenced once to observe device time
    # (target <2% overhead on the HIGGS mb=63 per-iter time). Enters
    # config_signature, so toggling retraces rather than reusing a
    # differently-fenced program
    tpu_trace: bool = False
    # directory for telemetry output (span + ledger JSONL, one record
    # per round flushed as it happens — a killed run keeps rounds 0..k).
    # Defaults to ./lgbt_trace when tpu_trace is on and no directory is
    # given
    tpu_trace_dir: str = ""
    # resilient training runtime (resilience/): directory for
    # full-training-state checkpoints — model text + the bagging/GOSS/
    # DART and feature-sampling RNG streams + the f32 score arrays +
    # iteration counter + early-stopping state — written atomically
    # (tmp + rename behind a MANIFEST.json pointer) every
    # tpu_checkpoint_freq rounds and once more on SIGTERM/SIGINT
    # preemption (the in-flight round finishes first). When the
    # directory already holds a valid manifest whose training signature
    # matches, engine.train auto-resumes from it and continues BITWISE-
    # identically to the uninterrupted run (bagging, multiclass and
    # valid-set early stopping included). Empty: checkpointing off —
    # the round loop takes one None check and issues zero device fences
    tpu_checkpoint_dir: str = ""
    # checkpoint cadence in rounds (with tpu_checkpoint_dir). 0 inherits
    # snapshot_freq when that is positive, else 10
    tpu_checkpoint_freq: int = 0
    # rolling retention shared by checkpoints and the CLI's
    # output_model.snapshot_iter_* files: keep the newest K, delete older
    tpu_snapshot_keep: int = 3
    # deterministic fault injection for tests/CI (also settable via the
    # LGBT_FAULTS environment variable): comma-separated "kill@R"
    # (SIGTERM to own pid before round R), "int@R" (SIGINT), and
    # "transient@N" (raise a retriable error at the N-th device
    # dispatch, 1-based). Every injected fault, retry and recovery is
    # recorded as a ledger note and an [Event] log record
    tpu_fault_spec: str = ""
    # bounded retry with exponential backoff around device dispatch
    # sites: how many times a transient dispatch error (injected, or an
    # XlaRuntimeError naming UNAVAILABLE / ABORTED / DEADLINE_EXCEEDED /
    # preemption) is retried before propagating. 0 disables the retry
    # wrapper entirely (dispatches become plain calls)
    tpu_retry_max: int = 2
    # first retry backoff in seconds; doubles on every further attempt
    tpu_retry_backoff_s: float = 0.05
    # serving service (lightgbm_tpu/serving/): HBM budget in MB for the
    # model registry's pool of device-resident forests. When the
    # resident models exceed it, least-recently-USED entries are evicted
    # (the entry just loaded is never the victim; a single model larger
    # than the whole budget loads with a warning). 0 = unbounded
    tpu_serve_hbm_budget_mb: float = 0.0
    # serving latency SLO: how long the request coalescer may hold a
    # request waiting for batch-mates before flushing to the engine.
    # Larger values fill shape buckets better (throughput); smaller
    # values bound tail latency
    tpu_serve_max_batch_wait_ms: float = 2.0
    # serving batch cap in rows: the coalescer flushes early once the
    # queued rows for a model reach this (a bucket is full). Requests
    # are never split across batches; one larger than the cap flushes
    # alone and the engine chunks it internally
    tpu_serve_max_batch_rows: int = 8192
    # train-to-serve hot-swap: poll interval in seconds at which the
    # serving watcher re-reads a checkpoint directory's MANIFEST.json
    # pointer for a new version to warm and atomically swap in
    tpu_serve_watch_interval_s: float = 0.5
    # rows used to pre-warm a newly loaded/swapped serving engine
    # on-device (compiles the pow2-bucket program before the first real
    # request; swap additionally re-warms the buckets live traffic
    # used). 0 disables warming
    tpu_serve_warm_rows: int = 256
    # live metrics plane (obs/metrics.py + obs/memory.py): feed the
    # process-wide registry from the training round loop — rounds,
    # retraces, aligned fallbacks, retry events, per-round latency
    # histogram — and refresh the HBM accountant gauges. Off by default:
    # the round loop then pays one attribute check and adds zero device
    # fences. Read via bst.metrics_snapshot(); serving exposes the same
    # registry over HTTP (tpu_serve_metrics_port)
    tpu_metrics: bool = False
    # serving /metrics exporter: TCP port for the ServingService's HTTP
    # endpoint — Prometheus text at /metrics (request counters,
    # coalescer batch fill, LRU evictions, per-model latency histograms
    # with p50/p99, live + peak HBM gauges) and the same snapshot as
    # JSON at /metrics.json. Binds 127.0.0.1. 0 disables the exporter
    tpu_serve_metrics_port: int = 0
    # keep the task=serve process alive this many seconds after loading
    # and scoring finish (0 = exit immediately): the window in which
    # scrapers hit the /metrics exporter and checkpoint watchers may
    # hot-swap. SIGINT/SIGTERM end the hold early and exit cleanly
    tpu_serve_hold_s: float = 0.0
    # request-scoped serving tracer (obs/reqtrace.py): every
    # Coalescer.submit mints a trace ID whose span records queue-wait,
    # batch id, flush reason (full vs deadline), batch fill ratio,
    # engine dispatch time share and total latency — even when the
    # batched engine call raises. Records land in a fixed in-memory ring
    # (served at the exporter's /debug/requests) and a tail-sampled
    # JSONL stream, and feed per-model SLO burn-rate gauges. Off by
    # default and free when off: the coalescer hot path pays one is-None
    # branch and zero device fences. Runtime-only: excluded from model
    # text and checkpoint signatures
    tpu_serve_trace: bool = False
    # directory for the request-trace JSONL stream
    # (reqtrace-<pid>.jsonl: one header line, then one row per KEPT
    # request, flushed per line so a killed host keeps everything so
    # far). Empty: ring buffer + /debug/requests only, no file
    tpu_serve_trace_dir: str = ""
    # head-sampling rate in [0, 1] for the request-trace JSONL stream: a
    # non-breaching request is kept when a deterministic hash of its
    # trace ID falls under this rate (no RNG — the same traffic keeps
    # the same rows on every run). Requests breaching tpu_serve_slo_ms
    # and errored requests are ALWAYS kept, so 0.0 is pure tail
    # sampling: SLO breachers and failures only
    tpu_serve_trace_sample: float = 0.0
    # request rows retained in the in-memory trace ring behind the
    # exporter's /debug/requests endpoint (oldest overwritten first);
    # registry load/swap/evict markers share the same ring
    tpu_serve_trace_ring: int = 512
    # per-request latency SLO in milliseconds for the serving plane. A
    # request whose submit-to-result latency exceeds it is a breach:
    # always kept in the trace stream, counted in
    # serve_slo_breaches_total, surfaced as a rate-limited
    # serve_request_slow event, and folded into the rolling per-model
    # serve_slo_burn_rate gauge — the admission/load-shedding signal.
    # 0 disables SLO classification (nothing breaches)
    tpu_serve_slo_ms: float = 0.0
    # AOT serving-artifact directory (serve/aot.py): jax.export
    # serialized forest-traversal programs keyed by an artifact
    # signature (jax version, backend, dtype plan, forest shape). At
    # model load the registry attaches matching buckets so a fresh
    # process reaches first score with zero new jax traces; a signature
    # mismatch emits a serve_aot event and falls back to normal jit.
    # Write artifacts with tools/serve_export.py. Empty disables.
    # Runtime-only: excluded from model text and checkpoint signatures
    tpu_serve_aot_dir: str = ""
    # compact residency plan for served forests: "off" (f32 engine,
    # bit-exact f64 routing), "f16" (thresholds + leaf values as
    # float16), or "int8" (per-feature affine int8 thresholds, the
    # ops/histogram.quantize_gh per-column scale discipline, f16
    # leaves). Compact engines route on f32 compares, so every load is
    # parity-gated against the f64 oracle: failing the gate emits
    # serve_compact_fallback and keeps the f32 engine — never silent
    # drift. Roughly 2.2x more models fit the same
    # tpu_serve_hbm_budget_mb. Runtime-only: excluded from model text
    # and checkpoint signatures
    tpu_serve_compact: str = "off"
    # parity-gate tolerance for compact plans: max |compact - oracle|
    # margin error allowed, relative to max(1, max |oracle margin|)
    # over the probe batch. Exceeding it rejects the compact plan for
    # that model (serve_compact_fallback). Runtime-only: excluded from
    # model text and checkpoint signatures
    tpu_serve_compact_tol: float = 0.05
    # serving network front door (serving/frontend/): TCP port for the
    # scoring HTTP endpoint — POST /v1/score/<model> (JSON rows or
    # packed-binary float rows) submitted through QoS admission into
    # the request coalescer, GET /healthz readiness. Binds 127.0.0.1.
    # 0 disables the front door. Runtime-only: excluded from model
    # text and checkpoint signatures, like the other serving knobs
    tpu_serve_port: int = 0
    # per-model QoS classes for front-door admission:
    # "model:class,..." with classes gold (highest, never shed),
    # silver, bronze (or 0/1/2). A "default:class" item sets the class
    # of unlisted models; without one they serve as bronze. Higher
    # classes dispatch first under saturation; lower classes are load-
    # shed (fast 429 + serve_shed event) while a model's SLO burn rate
    # is above the shed watermark
    tpu_serve_qos: str = ""
    # front-door load shedding: "auto" (shed exactly when the request
    # tracer + SLO are live, i.e. tpu_serve_trace with a nonzero
    # tpu_serve_slo_ms), "on", or "off". Shedding trips per model on
    # the rolling serve_slo_burn_rate gauge (obs/reqtrace.py) with
    # hysteresis, sheds only classes below gold, and clears when the
    # burn rate falls back under the clear watermark
    tpu_serve_shed: str = "auto"
    # SLO burn rate at or above which front-door shedding trips for a
    # model (fraction of breaching/errored requests over the rolling
    # burn window)
    tpu_serve_shed_high: float = 0.5
    # burn rate at or below which a tripped model stops shedding (must
    # be < tpu_serve_shed_high; the gap is the hysteresis band)
    tpu_serve_shed_low: float = 0.25
    # admission window in rows: the front-door dispatcher keeps at most
    # this many rows in flight toward the coalescer; excess requests
    # wait in per-class priority queues (highest class dispatches
    # first). 0 = twice tpu_serve_max_batch_rows
    tpu_serve_admit_rows: int = 0
    # devices the serving placer spreads models across: 1 (default)
    # keeps every forest on the default device and the placer off;
    # 0 = all visible devices; N > 1 = the first N. With more than one
    # device the per-model forests are pinned per device by HBM
    # headroom, hot models are replicated (serve_place events), each
    # batch routes to the replica with the shallowest queue, and
    # tpu_serve_hbm_budget_mb becomes a PER-DEVICE budget with
    # per-device LRU eviction of replicas
    tpu_serve_devices: int = 1
    # replica ceiling per model for the placer's hot-model replication
    # (request-rate ranked; replication only fills free per-device
    # headroom, it never evicts for a copy)
    tpu_serve_replicas: int = 2
    # runtime lock-discipline assertions (utils/locks.py): install a
    # checking __setattr__ on the serving/metrics classes whose shared
    # state is declared `# guarded-by:` — a guarded attribute rebound
    # outside its lock is recorded as a violation (read via
    # locks.violations(); the slow serving stress test asserts zero).
    # The dynamic twin of graftlint's static LGT004 rule. Off by
    # default and free when off (no wrapper is installed). Also
    # settable via the LGBT_DEBUG_LOCKS environment variable.
    # Runtime-only: excluded from model text and checkpoint signatures
    tpu_debug_locks: bool = False
    # in-run bottleneck profiler (obs/profiler.py): "off" (default,
    # zero added fences — one is-None branch per round), "on", or
    # "auto" (= on only when tpu_trace or tpu_metrics is already
    # enabled). On sampled rounds the round's device time is fenced
    # per dispatch site into a canonical terms_ms dict (ledger round
    # record, train_term_ms metrics gauges, bench terms_by_stage), the
    # fused build is decomposed once by in-run chained-k calibration,
    # and XLA cost_analysis() for every registered program lands in
    # program_costs.json. Runtime-only: excluded from model text and
    # checkpoint signatures, like tpu_metrics
    tpu_profile: str = "off"
    # profile every Nth round (round 0 is never sampled — it pays the
    # XLA compiles). Sampled rounds serialize the pipeline, so keep
    # this sparse on real runs; their wall time is excluded from the
    # train_round_ms histogram and marked timing="fenced" in the ledger
    tpu_profile_every: int = 50
    # "start:stop" round window bracketed in a programmatic
    # jax.profiler trace; artifact directory paths land in
    # trace_summary.json. Empty disables capture
    tpu_profile_capture: str = ""
    # unified run timeline (obs/timeline.py): "auto" (default — live
    # exactly when tpu_trace is), "on", or "off". Live, the CLI and
    # bench write a Chrome-trace/Perfetto timeline.json next to
    # trace_summary.json joining every JSONL/event stream on one
    # monotonic clock, the round loop runs the zero-fence rolling-
    # median anomaly watch (round_anomaly ledger notes + events), and
    # profiler-sampled rounds of distributed runs fence per shard —
    # per-device terms_ms columns, imbalance ratio, and the
    # edge-triggered dist_straggler / sweep_subfleet_imbalance
    # watches. Off adds zero fences and zero work. Runtime-only:
    # excluded from model text and checkpoint signatures
    tpu_timeline: str = "auto"
    # imbalance ratio (max/median per-device or per-sub-fleet round
    # time) at or above which the straggler watch counts a sampled
    # round as imbalanced. Runtime-only, like tpu_timeline
    tpu_straggler_threshold: float = 1.5
    # consecutive imbalanced sampled rounds before the edge-triggered
    # straggler event fires (and consecutive calm rounds below the
    # hysteresis clear level before it clears). Runtime-only
    tpu_straggler_rounds: int = 3
    # anomaly factor N for the in-run round-wall watch: a traced
    # round's wall > N x the trailing-window median commits a
    # round_anomaly ledger note + event (pure host arithmetic, zero
    # fences). 0 disables the watch. Runtime-only
    tpu_anomaly_factor: float = 3.0
    # trailing window length in rounds for the anomaly median;
    # anomalous rounds never enter the window. The watch arms after
    # window/4 (at least 3) normal rounds. Runtime-only
    tpu_anomaly_window: int = 32
    # many-model sweep trainer (sweep/train_many): "auto" partitions
    # the fleet into shape-bucketed sub-fleets (sweep/subfleet.py) and
    # batches each into one vmapped round program — GBDT, GOSS, and
    # DART fleets, quantized histograms included, with the sweep grid
    # (learning_rate, lambda_l1/l2, bagging seed+freq,
    # feature_fraction_seed) as traced operands — falling back to an
    # interleaved round-robin of per-model rounds for anything the gate
    # rejects; "batched" raises instead of falling back; "interleaved"
    # forces the fallback. Runtime-only: excluded from model text and
    # checkpoint signatures — model bytes are identical across modes
    tpu_sweep_mode: str = "auto"
    # fleet checkpoint directory for train_many (MANIFEST.json + per-
    # model texts + score planes + host RNG). Empty disables fleet
    # checkpointing. Runtime-only, like tpu_checkpoint_dir
    tpu_sweep_checkpoint_dir: str = ""
    # write a fleet checkpoint every N sweep rounds (0 = never).
    # Runtime-only, like tpu_checkpoint_freq
    tpu_sweep_checkpoint_freq: int = 0
    # HBM budget in MiB for one batched sub-fleet's score stack (0 =
    # ask the obs/memory accountant for device headroom, unbounded when
    # the runtime has no memory_stats — e.g. CPU emulation). Fleets
    # whose [M, K, N] stack would exceed it split into pow2-sized
    # sub-fleets. Runtime-only, like tpu_sweep_mode
    tpu_sweep_hbm_budget_mb: int = 0
    # hard cap on models per batched sub-fleet (0 = uncapped); applied
    # after the HBM budget. Runtime-only, like tpu_sweep_mode
    tpu_sweep_max_fleet: int = 0

    # internal (set by trainer, reference config.h:832-833)
    is_parallel: bool = False
    is_parallel_find_bin: bool = False

    # ------------------------------------------------------------------
    @staticmethod
    def canonical_name(key: str) -> str:
        k = key.strip().lower()
        return _ALIASES.get(k, k)

    def __post_init__(self) -> None:
        if isinstance(self.task, dict):
            raise TypeError("Config() takes dataclass fields, not a params "
                            "dict — use Config.from_params({...})")

    @classmethod
    def from_params(cls, params: Optional[Dict[str, Any]] = None) -> "Config":
        cfg = cls()
        cfg.update(params or {})
        return cfg

    def update(self, params: Dict[str, Any]) -> "Config":
        """Apply key=value params with alias expansion.

        First-one-wins among aliases of the same canonical key, matching the
        reference `KV2Map` + alias pass (`src/io/config.cpp:15-40`).
        """
        fields = {f.name: f for f in dataclasses.fields(self)}
        seen = set()
        for key, value in params.items():
            name = self.canonical_name(key)
            if name in seen:
                continue
            if name not in fields:
                # unknown keys are tolerated (reference warns); keep for users
                continue
            seen.add(name)
            f = fields[name]
            if f.type in ("int", int):
                setattr(self, name, int(float(value)))
            elif f.type in ("float", float):
                setattr(self, name, float(value))
            elif f.type in ("bool", bool):
                setattr(self, name, _to_bool(value))
            elif name in ("valid", "valid_initscore_filenames", "metric"):
                setattr(self, name, _kv_list(value, str))
            elif name in ("monotone_constraints",):
                setattr(self, name, _kv_list(value, int))
            elif name == "eval_at":
                setattr(self, name, sorted(_kv_list(value, int)))
            elif name in ("feature_contri", "label_gain",
                          "cegb_penalty_feature_lazy",
                          "cegb_penalty_feature_coupled"):
                setattr(self, name, _kv_list(value, float))
            else:
                setattr(self, name, str(value))
        self._normalize()
        self._check_conflicts()
        cache_dir = self.tpu_compile_cache_dir or os.environ.get(
            "LGBT_COMPILE_CACHE_DIR", "")
        if cache_dir:
            # Wire jax's persistent compilation cache before any trace
            # happens (Config.update always precedes Dataset/Booster
            # construction). One-shot per process; see compile_cache.py.
            from . import compile_cache
            compile_cache.init_persistent_cache(cache_dir)
        return self

    # ------------------------------------------------------------------
    def _normalize(self) -> None:
        """Normalize enum-ish strings (reference `config.cpp:121-151`)."""
        obj = self.objective.strip().lower()
        self.objective = _OBJECTIVE_ALIASES.get(obj, obj)
        self.boosting = _BOOSTING_ALIASES.get(self.boosting.strip().lower(),
                                              self.boosting.strip().lower())
        self.tree_learner = _TREE_LEARNER_ALIASES.get(
            self.tree_learner.strip().lower(), self.tree_learner.strip().lower())
        self.device_type = _DEVICE_ALIASES.get(self.device_type.strip().lower(),
                                               self.device_type.strip().lower())
        self.metric = [_METRIC_ALIASES.get(m.strip().lower(), m.strip().lower())
                       for m in self.metric]
        if not self.label_gain:
            # default label gain 2^i - 1 (reference config.h:715-722)
            self.label_gain = [float((1 << i) - 1) for i in range(31)]
        self.tpu_serve_compact = self.tpu_serve_compact.strip().lower()
        if self.tpu_serve_compact not in ("off", "f16", "int8"):
            raise ValueError(
                f"tpu_serve_compact must be off/f16/int8, got "
                f"{self.tpu_serve_compact!r}")
        self.tpu_timeline = self.tpu_timeline.strip().lower()
        if self.tpu_timeline not in ("off", "on", "auto"):
            raise ValueError(
                f"tpu_timeline must be off/on/auto, got "
                f"{self.tpu_timeline!r}")
        self.tpu_serve_shed = self.tpu_serve_shed.strip().lower()
        if self.tpu_serve_shed not in ("off", "on", "auto"):
            raise ValueError(
                f"tpu_serve_shed must be off/on/auto, got "
                f"{self.tpu_serve_shed!r}")
        if not 0.0 < self.tpu_serve_shed_low < self.tpu_serve_shed_high \
                <= 1.0:
            raise ValueError(
                "need 0 < tpu_serve_shed_low < tpu_serve_shed_high <= 1, "
                f"got low={self.tpu_serve_shed_low!r} "
                f"high={self.tpu_serve_shed_high!r}")
        if self.tpu_serve_qos:
            # full parsing lives in serving/frontend/qos.py; the config
            # layer rejects syntactically-broken specs at startup
            from .serving.frontend.qos import parse_qos
            parse_qos(self.tpu_serve_qos)

    def _check_conflicts(self) -> None:
        """Parameter-conflict resolution (reference `CheckParamConflict`
        `src/io/config.cpp:204-283`)."""
        if self.is_provide_training_metric or self.valid:
            pass
        if self.tree_learner != "serial":
            self.is_parallel = True
            # distributed construction also finds bins through the
            # global-sync path (dist/binning.py) — per-shard sample
            # passes merged into boundaries identical on every shard
            # (reference CheckParamConflict sets the same flag for
            # parallel learners, config.cpp:232-238)
            self.is_parallel_find_bin = True
            if self.num_machines <= 1:
                # single machine: fall back to serial semantics but keep the
                # learner (it degrades to a 1-shard mesh)
                pass
        if self.boosting == "rf":
            if not (self.bagging_fraction < 1.0 or self.pos_bagging_fraction < 1.0
                    or self.neg_bagging_fraction < 1.0):
                self.bagging_fraction = 0.9
            if self.bagging_freq <= 0:
                self.bagging_freq = 1
        if self.boosting == "goss":
            # GOSS owns its sampling; plain bagging is disabled
            self.bagging_freq = 0
        if (self.pos_bagging_fraction < 1.0 or self.neg_bagging_fraction < 1.0) \
                and self.objective != "binary":
            self.pos_bagging_fraction = 1.0
            self.neg_bagging_fraction = 1.0
        if self.num_class > 1 and self.objective not in (
                "multiclass", "multiclassova", "none"):
            if self.objective in ("regression",) and self.num_class == 1:
                pass
        if self.max_depth > 0:
            full = 1 << min(self.max_depth, 30)
            self.num_leaves = min(self.num_leaves, full)

    # ------------------------------------------------------------------
    @property
    def forces_host_learner(self) -> bool:
        """True when config alone routes training to the host
        SerialTreeLearner. Forced splits and CEGB split/coupled
        penalties run on the fused DEVICE learner (round 5); only the
        per-(row, feature) LAZY penalties keep the host twin (their
        marking state has no bounded device representation).
        GBDT.use_fused and Dataset._maybe_bundle must agree on this, so
        it lives in one place."""
        return len(self.cegb_penalty_feature_lazy) > 0

    @property
    def sequential_device_only(self) -> bool:
        """True when the config needs the strictly SEQUENTIAL device
        tree loop (fused builder): forced splits and CEGB penalties
        depend on commit order, which the speculative aligned/level
        engines replay out of order."""
        return bool(self.forcedsplits_filename) \
            or self.cegb_penalty_split > 0 \
            or len(self.cegb_penalty_feature_coupled) > 0 \
            or len(self.cegb_penalty_feature_lazy) > 0

    @property
    def num_tree_per_iteration(self) -> int:
        if self.objective == "multiclass" or self.objective == "multiclassova":
            return self.num_class
        return 1

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    def clone(self) -> "Config":
        return dataclasses.replace(
            self,
            valid=list(self.valid),
            metric=list(self.metric),
            monotone_constraints=list(self.monotone_constraints),
            feature_contri=list(self.feature_contri),
            label_gain=list(self.label_gain),
            eval_at=list(self.eval_at),
        )


def parse_config_file(text: str) -> Dict[str, str]:
    """Parse a reference-style `train.conf` (`key = value` lines, `#` comments;
    reference `Config::LoadFromString`, `src/io/config.cpp`)."""
    out: Dict[str, str] = {}
    for line in text.splitlines():
        line = line.split("#", 1)[0].strip()
        if not line or "=" not in line:
            continue
        k, v = line.split("=", 1)
        out[k.strip()] = v.strip()
    return out
