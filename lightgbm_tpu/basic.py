"""Public Dataset / Booster API.

Re-creates the reference python package surface
(`python-package/lightgbm/basic.py`): lazily-constructed `Dataset` with
reference alignment for validation sets, field set/get, and a `Booster` with
`update/eval/predict/save_model/model_to_string/feature_importance` — except
the ctypes/C-API indirection is gone: the booster drives the JAX GBDT core
directly (the reference's one-C-call-per-iteration boundary becomes one
host->device step per iteration).
"""
from __future__ import annotations

import copy
import json
from typing import Any, Dict, List, Optional, Sequence, Union

import time

import numpy as np

from .config import Config
from .io.dataset import Dataset as _CoreDataset
from .models.boosting_variants import create_boosting
from .models.gbdt import GBDT
from .models.model_text import (dump_model_json, load_model_from_string,
                                save_model_to_string, _feature_infos)
from .models.tree import Tree
from .ops.metrics import create_metrics, metric_names
from .ops.objectives import create_objective
from .ops.predict import flatten_forest, predict_raw_values


def _native_predict(trees, X, num_class: int, pred_leaf: bool = False,
                    flat=None, es_freq: int = 0, es_margin: float = 0.0):
    """Batch predict through the native OpenMP predictor
    (src/native/predictor.cpp); None -> caller uses the NumPy walk."""
    from . import native
    if not trees or not native.native_available():
        return None
    if flat is None:
        flat = flatten_forest(trees, num_class)
    if X.shape[1] <= int(flat["feat"].max(initial=-1)):
        raise ValueError(
            f"data has {X.shape[1]} features but the model was trained "
            f"with at least {int(flat['feat'].max()) + 1}")
    out = native.predict_forest(np.asarray(X, np.float64), flat,
                                num_class, pred_leaf, es_freq, es_margin)
    if out is None or pred_leaf:
        return out
    return out.reshape(len(X), num_class) if out.ndim == 1 else out


def _early_stop_predict_py(trees, X, num_class: int, es_freq: int,
                           es_margin: float) -> np.ndarray:
    """Pure-Python fallback for prediction early stopping (reference
    prediction_early_stop.cpp): per row, walk trees until the margin test
    passes at a freq boundary. `es_freq` is in TREES (the caller scales
    the per-iteration freq by num_class so checks land on iteration
    boundaries, like the reference)."""
    X = np.asarray(X, np.float64)
    n = len(X)
    out = np.zeros((n, num_class), np.float64)
    for i in range(n):
        acc = out[i]
        for t, tree in enumerate(trees):
            acc[t % num_class] += tree.predict_row(X[i])
            if es_freq > 0 and (t + 1) % es_freq == 0 and t + 1 < len(trees):
                if num_class <= 1:
                    if abs(acc[0]) > es_margin:
                        break
                else:
                    top = np.sort(acc)[-2:]
                    if top[1] - top[0] > es_margin:
                        break
    return out


class LightGBMError(Exception):
    pass


def _to_matrix(data) -> np.ndarray:
    if isinstance(data, np.ndarray):
        return data.astype(np.float64, copy=False)
    if isinstance(data, (list, tuple)):
        return np.asarray(data, np.float64)
    if hasattr(data, "values"):  # pandas
        return _data_from_pandas(data)[0]  # categories re-derived; callers
        # needing train-time alignment pass pandas_categorical explicitly
    if hasattr(data, "toarray"):  # scipy sparse
        return np.asarray(data.toarray(), np.float64)
    raise LightGBMError(f"Cannot convert data of type {type(data)}")


def _data_from_pandas(df, pandas_categorical=None):
    """DataFrame -> (matrix, feature_names, cat columns, cat categories).

    Mirrors the reference's pandas handling (basic.py:255-298): `category`
    dtype columns become their integer codes (NaN -> -1 -> missing), object
    columns are rejected, and column names become feature names. When
    `pandas_categorical` (the TRAINING category lists, in categorical-
    column order) is given, codes are remapped onto those categories so
    predict-time frames with different category sets stay aligned
    (reference stores pandas_categorical in the model for this)."""
    feature_names = [str(c) for c in df.columns]
    cat_cols = []
    cat_categories = []
    arrs = []
    cat_i = 0
    for i, col in enumerate(df.columns):
        s = df[col]
        if str(s.dtype) == "category":
            cat_cols.append(i)
            if pandas_categorical is not None:
                if cat_i >= len(pandas_categorical):
                    raise LightGBMError(
                        "train and predict DataFrames have different "
                        "numbers of categorical columns")
                train_cats = list(pandas_categorical[cat_i])
                s = s.cat.set_categories(train_cats)
            cat_categories.append([c for c in s.cat.categories])
            cat_i += 1
            codes = s.cat.codes.to_numpy().astype(np.float64)
            codes = np.where(codes < 0, np.nan, codes)
            arrs.append(codes)
        elif s.dtype == object:
            raise LightGBMError(
                f"DataFrame.dtypes for column {col} must be int, float or "
                "bool (or category)")
        else:
            arrs.append(s.to_numpy().astype(np.float64))
    return np.column_stack(arrs) if arrs else np.empty((len(df), 0)), \
        feature_names, cat_cols, cat_categories


class Dataset:
    """Lazily-constructed dataset (reference basic.py:600+)."""

    def __init__(self, data, label=None, reference: "Dataset" = None,
                 weight=None, group=None, init_score=None,
                 feature_name: Union[str, List[str]] = "auto",
                 categorical_feature: Union[str, List[int]] = "auto",
                 params: Optional[Dict] = None, free_raw_data: bool = True,
                 silent: bool = False) -> None:
        self.data = data
        self.label = label
        self.reference = reference
        self.weight = weight
        self.group = group
        self.init_score = init_score
        self.feature_name = feature_name
        self.categorical_feature = categorical_feature
        self.params = dict(params or {})
        self.free_raw_data = free_raw_data
        self._handle: Optional[_CoreDataset] = None
        self.used_indices: Optional[np.ndarray] = None
        self.pandas_categorical = None
        self._predictor = None

    # ------------------------------------------------------------------
    def construct(self) -> "Dataset":
        if self._handle is not None:
            return self
        if self.reference is not None:
            ref = self.reference.construct()._handle
        else:
            ref = None
        if self.used_indices is not None:
            # subset of the (constructed) reference (basic.py subset path)
            parent = self.reference.construct()._handle
            self._handle = parent.subset(self.used_indices)
            if self.label is not None:
                self._handle.metadata.set_label(self.label)
            if self.group is not None:
                self._handle.metadata.set_group(self.group)
            return self
        cfg = Config.from_params(self.params)
        if isinstance(self.data, str):
            # file-path construction (reference basic.py: Dataset accepts
            # a path; two_round=True in params streams it in O(chunk)
            # host memory through the loader's push-rows flow). The
            # constructor's categorical_feature argument folds into the
            # config spec the loader reads (the reference folds it into
            # params the same way for file inputs).
            if self.categorical_feature not in ("auto", None):
                cats = list(self.categorical_feature)
                if any(isinstance(c, str) for c in cats):
                    cfg.categorical_feature = "name:" + ",".join(
                        str(c) for c in cats)
                else:
                    cfg.categorical_feature = ",".join(
                        str(int(c)) for c in cats)
            from .io.loader import DatasetLoader
            loader = DatasetLoader(cfg)
            if ref is not None:
                self._handle = loader.\
                    load_from_file_align_with_other_dataset(self.data, ref)
            else:
                self._handle = loader.load_from_file(self.data)
            if self.label is not None:
                self._handle.metadata.set_label(self.label)
            if self.weight is not None:
                self._handle.metadata.set_weight(self.weight)
            if self.group is not None:
                self._handle.metadata.set_group(self.group)
            if self.init_score is not None:
                self._handle.metadata.set_init_score(self.init_score)
            return self
        feature_names = (None if self.feature_name in ("auto", None)
                         else list(self.feature_name))
        raw_cats = (None if self.categorical_feature in ("auto", None)
                    else list(self.categorical_feature))
        sparse_in = (hasattr(self.data, "tocsc")
                     and not isinstance(self.data, np.ndarray))
        if hasattr(self.data, "values") and hasattr(self.data, "columns"):
            mat, pd_names, pd_cats, pd_categories = \
                _data_from_pandas(self.data)
            if feature_names is None:
                feature_names = pd_names
            if raw_cats is None and pd_cats:
                raw_cats = pd_cats
            self.pandas_categorical = pd_categories or None
        elif sparse_in:
            mat = self.data   # CSR/CSC stays sparse (from_sparse ingest)
        else:
            mat = _to_matrix(self.data)
        cats = None
        if raw_cats is not None:
            cats = []
            for c in raw_cats:
                if isinstance(c, str):
                    # column-name form (the standard pandas idiom,
                    # reference basic.py categorical_feature handling)
                    if feature_names is None or c not in feature_names:
                        raise LightGBMError(
                            f"Unknown categorical feature name: {c!r}")
                    cats.append(feature_names.index(c))
                else:
                    cats.append(int(c))
        if not sparse_in and int(getattr(cfg, "tpu_stream_chunk_rows",
                                         0)) > 0:
            # streaming out-of-core ingest: chunked device-side binning
            # (io/stream.py), same sample draw -> same model bytes
            from .io.stream import stream_matrix as maker
        else:
            maker = (_CoreDataset.from_sparse if sparse_in
                     else _CoreDataset.from_matrix)
        self._handle = maker(
            mat, label=self.label, config=cfg, weight=self.weight,
            group=self.group, init_score=self.init_score,
            feature_names=feature_names, categorical_feature=cats,
            reference=ref)
        if self.free_raw_data:
            self.data = None
        return self

    # ------------------------------------------------------------------
    def create_valid(self, data, label=None, weight=None, group=None,
                     init_score=None, params=None) -> "Dataset":
        return Dataset(data, label=label, reference=self, weight=weight,
                       group=group, init_score=init_score,
                       params=params or self.params)

    def subset(self, used_indices, params=None) -> "Dataset":
        ds = Dataset(None, reference=self,
                     params=params or self.params)
        ds.used_indices = np.asarray(used_indices, np.int64)
        return ds

    # ------------------------------------------------------------------
    def set_label(self, label) -> "Dataset":
        self.label = label
        if self._handle is not None and label is not None:
            self._handle.metadata.set_label(label)
        return self

    def set_weight(self, weight) -> "Dataset":
        self.weight = weight
        if self._handle is not None:
            self._handle.metadata.set_weight(weight)
        return self

    def set_group(self, group) -> "Dataset":
        self.group = group
        if self._handle is not None:
            self._handle.metadata.set_group(group)
        return self

    def set_init_score(self, init_score) -> "Dataset":
        self.init_score = init_score
        if self._handle is not None:
            self._handle.metadata.set_init_score(init_score)
        return self

    def get_label(self):
        if self._handle is not None and self._handle.metadata.label is not None:
            return np.asarray(self._handle.metadata.label)
        return self.label

    def get_weight(self):
        if self._handle is not None and self._handle.metadata.weight is not None:
            return np.asarray(self._handle.metadata.weight)
        return self.weight

    def get_group(self):
        if self._handle is not None and \
                self._handle.metadata.query_boundaries is not None:
            return np.diff(self._handle.metadata.query_boundaries)
        return self.group

    def get_init_score(self):
        return self.init_score

    def get_field(self, name):
        return {"label": self.get_label, "weight": self.get_weight,
                "group": self.get_group,
                "init_score": self.get_init_score}[name]()

    def set_field(self, name, data):
        return {"label": self.set_label, "weight": self.set_weight,
                "group": self.set_group,
                "init_score": self.set_init_score}[name](data)

    @property
    def num_data(self) -> int:
        self.construct()
        return self._handle.num_data

    @property
    def num_feature(self) -> int:
        self.construct()
        return self._handle.num_total_features

    def save_binary(self, filename: str) -> "Dataset":
        self.construct()
        self._handle.save_binary(filename)
        return self

    def add_features_from(self, other: "Dataset") -> "Dataset":
        """Append `other`'s features column-wise (reference
        basic.py add_features_from -> LGBM_DatasetAddFeaturesFrom;
        both datasets must be constructed over the same rows)."""
        self.construct()
        other.construct()
        self._handle.add_features_from(other._handle)
        return self

    def _update_params(self, params) -> "Dataset":
        self.params.update(params or {})
        return self


class Booster:
    """reference basic.py:1578 Booster."""

    def __init__(self, params: Optional[Dict] = None,
                 train_set: Optional[Dataset] = None,
                 model_file: Optional[str] = None,
                 model_str: Optional[str] = None,
                 silent: bool = False) -> None:
        self.params = dict(params or {})
        self.best_iteration = -1
        self.best_score: Dict = {}
        self._flat_cache: Optional[tuple] = None
        self._engine_cache: Dict[tuple, Any] = {}
        self._predict_engine_calls = 0
        self._predict_fallback_calls = 0
        self._predict_route_last: Optional[bool] = None
        self._model_gen = 0
        self.pandas_categorical = None
        self._train_set = train_set
        self._gbdt: Optional[GBDT] = None
        self._telemetry = None  # engine.train parks the ledger here
        self._profiler = None   # ... and the in-run profiler
        self._loaded: Optional[Dict] = None
        self._name_valid_sets: List[str] = []
        self._valid_sets_public: List["Dataset"] = []
        self.name_train_set = "training"
        if model_file is not None:
            from .io.file_io import open_file
            with open_file(model_file) as fh:
                self._init_from_string(fh.read())
        elif model_str is not None:
            self._init_from_string(model_str)
        elif train_set is not None:
            if not isinstance(train_set, Dataset):
                raise TypeError("Training data should be Dataset instance")
            train_set.construct()
            self.pandas_categorical = train_set.pandas_categorical
            cfg = Config.from_params(self.params)
            self._cfg = cfg
            self._gbdt = create_boosting(cfg, train_set._handle)
        else:
            raise LightGBMError(
                "need at least one of train_set/model_file/model_str")

    # ------------------------------------------------------------------
    def _init_from_string(self, text: str) -> None:
        for line in text.splitlines():
            if line.startswith("pandas_categorical:"):
                try:
                    self.pandas_categorical = json.loads(
                        line.split(":", 1)[1])
                except json.JSONDecodeError:
                    pass
        self._loaded = load_model_from_string(text)
        loaded_params = dict(self._loaded.get("params", {}))
        self.params = {**loaded_params, **self.params}
        # keep the model file's training params (regularization etc.) so
        # downstream refit/predict reuse them (reference GBDT::RefitTree
        # runs under the session config)
        self._cfg = Config.from_params(
            {**self.params,
             "objective": self._loaded["objective"].split(" ")[0],
             "num_class": self._loaded["num_class"]})

    @property
    def trees(self) -> List[Tree]:
        if self._gbdt is not None:
            return self._gbdt.materialized_models()
        return self._loaded["trees"] if self._loaded else []

    @property
    def num_tree_per_iteration(self) -> int:
        if self._gbdt is not None:
            return self._gbdt.num_tree_per_iteration
        return self._loaded.get("num_tree_per_iteration", 1)

    @property
    def telemetry(self):
        """The training RoundLedger (obs/ledger.py) when `tpu_trace` is
        on; None otherwise."""
        return getattr(self._gbdt, "telemetry", None) or self._telemetry

    @property
    def profiler(self):
        """The in-run RoundProfiler (obs/profiler.py) when `tpu_profile`
        resolved to enabled; None otherwise. Carries sampled-round
        terms_ms history, the build calibration, and the artifact
        writers (summary / write_program_costs)."""
        return getattr(self._gbdt, "_profiler", None) or self._profiler

    def metrics_snapshot(self):
        """Live metrics + HBM accounting snapshot — the API twin of the
        serving /metrics endpoint, parked like `bst.telemetry`: the
        registry is process-wide, so the snapshot survives the
        engine.train round-trip onto the fresh booster. Keys:
        ``metrics`` (obs/metrics.py versioned snapshot: counters,
        gauges, histograms with p50/p99) and ``memory`` (obs/memory.py
        owner reconciliation). Counters are zero until something enables
        the plane (`tpu_metrics`, a serving exporter, or
        `obs.metrics.enable()`)."""
        from .obs import memory as obs_memory
        from .obs import metrics as obs_metrics
        return {"metrics": obs_metrics.snapshot(),
                "memory": obs_memory.snapshot()}

    # ------------------------------------------------------------------
    def add_valid(self, data: Dataset, name: str) -> "Booster":
        data.construct()
        self._gbdt.add_valid_dataset(data._handle)
        self._name_valid_sets.append(name)
        self._valid_sets_public.append(data)
        return self

    def update(self, train_set: Optional[Dataset] = None,
               fobj=None) -> bool:
        """One boosting iteration (reference basic.py:1846). Returns True if
        training finished (cannot split any more)."""
        _t0 = time.perf_counter()
        if fobj is not None:
            # custom gradients bypass the aligned engine's score lane:
            # sync the lazily-stale train scores and leave aligned mode
            # (the engine could not follow the external tree)
            if hasattr(self._gbdt, "_drop_aligned"):
                self._gbdt._drop_aligned()
            scores = self._gbdt.train_score.numpy()
            k = self.num_tree_per_iteration
            if k == 1:
                grad, hess = fobj(scores[0], self._train_set)
            else:
                grad, hess = fobj(scores.T, self._train_set)
            grad = np.asarray(grad, np.float32).reshape(k, -1)
            hess = np.asarray(hess, np.float32).reshape(k, -1)
            self._model_gen += 1
            out = self._gbdt.train_one_iter(grad, hess)
            self._log_iter_time(_t0)
            return out
        self._model_gen += 1
        out = self._gbdt.train_one_iter()
        self._log_iter_time(_t0)
        return out

    def _log_iter_time(self, t0: float) -> None:
        # reference logs per-iteration wall time (gbdt.cpp:285-288)
        from .utils import log as _log
        if _log._level >= _log.DEBUG:
            _log.debug("%.3fs elapsed, finished iteration %d"
                       % (time.perf_counter() - t0,
                          self._gbdt.num_iterations_trained))

    def rollback_one_iter(self) -> "Booster":
        self._gbdt.rollback_one_iter()
        self._model_gen += 1
        return self

    # ------------------------------------------------------------------
    def refit(self, data, label, decay_rate: float = 0.9,
              leaf_preds=None, **kwargs) -> "Booster":
        """Refit existing tree structures to new data (reference
        basic.py:2337 -> `GBDT::RefitTree` gbdt.cpp:297-320 ->
        `FitByExistingTree` serial_tree_learner.cpp:239-269):
        ``leaf_output = decay_rate * old + (1 - decay_rate) * new`` where
        ``new`` is the closed-form leaf output of the new data's grad/hess
        summed per (fixed) leaf assignment."""
        import jax.numpy as jnp

        from .io.dataset import Metadata
        from .ops.split import threshold_l1_host as _thl1

        trees = self.trees
        if not trees:
            raise LightGBMError("No trees to refit")
        X = _to_matrix(data)
        label = np.asarray(label, np.float64).reshape(-1)
        n = len(X)
        k = self.num_tree_per_iteration
        if leaf_preds is None:
            # all trees, regardless of best_iteration (reference refit
            # predicts with num_iteration=-1, basic.py:2362)
            leaf_preds = predict_raw_values(trees, X, leaf_index=True)
        leaf_preds = np.asarray(leaf_preds, np.int64).reshape(n, len(trees))
        cfg = self._cfg
        objective = create_objective(cfg)
        if objective is None:
            raise LightGBMError("Cannot refit due to null objective function.")
        md = Metadata(n)
        md.set_label(label)
        objective.init(md, n)
        scores = np.zeros((k, n), np.float64)
        l1, l2 = cfg.lambda_l1, cfg.lambda_l2
        mds = cfg.max_delta_step
        for it in range(len(trees) // k):
            g, h = objective.get_gradients(jnp.asarray(scores, jnp.float32))
            g = np.asarray(g, np.float64)
            h = np.asarray(h, np.float64)
            for tid in range(k):
                tree = trees[it * k + tid]
                lp = leaf_preds[:, it * k + tid]
                nl = tree.num_leaves
                sg = np.bincount(lp, weights=g[tid], minlength=nl)[:nl]
                sh = np.bincount(lp, weights=h[tid], minlength=nl)[:nl]
                out = -_thl1(sg, l1) / (sh + l2 + 1e-15)
                if mds > 0:
                    out = np.clip(out, -mds, mds)
                new_vals = (decay_rate * tree.leaf_value[:nl]
                            + (1.0 - decay_rate) * out * tree.shrinkage)
                tree.leaf_value[:nl] = new_vals
                scores[tid] += new_vals[lp]
        self._model_gen += 1
        return self

    @property
    def current_iteration(self) -> int:
        return self._gbdt.iter if self._gbdt else \
            len(self.trees) // max(1, self.num_tree_per_iteration)

    def num_trees(self) -> int:
        return len(self.trees)

    def num_model_per_iteration(self) -> int:
        return self.num_tree_per_iteration

    # ------------------------------------------------------------------
    def eval_train(self):
        return [(n, m, v, b) for n, m, v, b in self._gbdt.eval_train()]

    def eval_valid(self):
        out = []
        for i, res in enumerate(self._eval_valid_grouped()):
            name = self._name_valid_sets[i] if i < len(
                self._name_valid_sets) else f"valid_{i}"
            out.extend((name, m, v, b) for _, m, v, b in res)
        return out

    def _eval_valid_grouped(self):
        per_set: Dict[str, List] = {}
        res = self._gbdt.eval_valid()
        groups: Dict[str, List] = {}
        for item in res:
            groups.setdefault(item[0], []).append(item)
        return [groups[k] for k in sorted(groups)]

    # ------------------------------------------------------------------
    def predict(self, data, num_iteration: Optional[int] = None,
                raw_score: bool = False, pred_leaf: bool = False,
                pred_contrib: bool = False, start_iteration: int = 0,
                **kwargs) -> np.ndarray:
        if isinstance(data, str):
            # predict straight from a data file (reference
            # LGBM_BoosterPredictForFile, c_api.h:645-704)
            from .io.loader import DatasetLoader
            cfg = Config.from_params({**self.params, **kwargs})
            cfg.header = bool(kwargs.get("data_has_header",
                                         kwargs.get("header", cfg.header)))
            # label-free scoring files: when the file's column count
            # equals the MODEL's feature count there is no label column
            # to strip (the reference passes num_total_model_features to
            # the parser for exactly this detection, predictor.hpp:185)
            nf_model = (self._gbdt.train_data.num_total_features
                        if self._gbdt is not None else
                        self._loaded.get("max_feature_idx", -2) + 1)
            from .io.file_io import open_file
            with open_file(data, errors="replace") as f:
                if cfg.header:
                    f.readline()
                first = f.readline()
            ncols = 0
            if first.strip():
                if ":" in first and "," not in first:
                    ncols = -1          # libsvm: sparse, keep default
                else:
                    for sep in ("\t", ",", " "):
                        if sep in first:
                            ncols = len(first.rstrip("\r\n").split(sep))
                            break
            if ncols == nf_model:
                cfg.label_column = "-1"
                # ambiguity warning: a LABELED file with one fewer
                # feature than the model hits this same branch and would
                # silently shift every feature by one. Flag it when the
                # first column looks label-like (small integers).
                try:
                    tok = first.replace("\t", ",").replace(" ", ",") \
                        .split(",")[0]
                    v = float(tok)
                    if np.isfinite(v) and v == int(v) and 0 <= v <= 100:
                        from .utils import log
                        log.warning(
                            f"treating {data!r} as label-free because its "
                            f"column count ({ncols}) equals the model's "
                            f"feature count, but the first column looks "
                            f"label-like; if this file HAS labels, the "
                            f"features are mis-aligned — score a file "
                            f"with {nf_model + 1} columns or strip the "
                            f"label column")
                except ValueError:
                    pass
            _, feats, _ex = DatasetLoader(cfg).parse_file(data)
            if ncols == -1 and nf_model > 0 and feats.shape[1] < nf_model:
                # ragged LibSVM scoring rows: absent trailing features
                # are zero (reference sparse convention). Dense files
                # with too few columns stay unpadded — a feature-count
                # mismatch is an error, not missing data
                feats = np.pad(feats,
                               ((0, 0), (0, nf_model - feats.shape[1])))
            data = feats
        if hasattr(data, "tocsr") and not isinstance(data, np.ndarray):
            # CSR/CSC input (reference LGBM_BoosterPredictForCSR/CSC,
            # c_api.h:706-910): densify row CHUNKS under a constant
            # ~256 MB byte budget, never the full matrix
            rows_per = max(1, (256 << 20) // (8 * max(1, data.shape[1])))
            if data.shape[0] > rows_per:
                csr = data.tocsr()
                outs = []
                for lo in range(0, csr.shape[0], rows_per):
                    outs.append(self.predict(
                        csr[lo:lo + rows_per].toarray(), num_iteration,
                        raw_score, pred_leaf, pred_contrib,
                        start_iteration, **kwargs))
                return np.concatenate(outs, axis=0)
        if (self.pandas_categorical and hasattr(data, "columns")
                and hasattr(data, "values")):
            # remap predict-time category codes onto the TRAINING
            # categories (reference pandas_categorical model field)
            X = _data_from_pandas(data, self.pandas_categorical)[0]
        else:
            X = _to_matrix(data)
        k = self.num_tree_per_iteration
        if num_iteration is None or num_iteration <= 0:
            num_iteration = (self.best_iteration
                             if self.best_iteration > 0 else -1)
        s_iter = max(int(start_iteration or 0), 0)
        u_spec = num_iteration if num_iteration and num_iteration > 0 else -1
        trees = self.trees[s_iter * k:]
        if u_spec > 0:
            trees = trees[:u_spec * k]
        n = len(X)
        opts = {**self.params, **kwargs}
        obj_name = str(opts.get("objective", self.params.get(
            "objective", ""))).split(" ")[0]
        es_ok_obj = k > 1 or obj_name == "binary"
        es_on = (bool(opts.get("pred_early_stop", False)) and not raw_score
                 and es_ok_obj and not pred_leaf and not pred_contrib)
        from .native import native_available
        # serving-engine policy (serve/ForestEngine): depth-synchronized
        # device traversal with a cached, incrementally-updated stacked
        # forest. "auto" keeps the exact native/host walk on the CPU tier
        # unless no native library exists and the job is big enough to
        # amortize a compile.
        pd = str(opts.get("tpu_predict_device", "auto")).strip().lower()
        import jax
        use_engine = bool(trees) and not pred_contrib and (
            pd in ("on", "device", "true", "1")
            or (pd == "auto"
                and (jax.default_backend() != "cpu"
                     or (not native_available()
                         and n * len(trees) >= (1 << 18)))))
        # serve-engine routing counters on the structured channel: one
        # event per ROUTE CHANGE (not per call), so scoring loops stay
        # quiet while a silent fall-off-the-engine is still visible
        if use_engine:
            self._predict_engine_calls += 1
        else:
            self._predict_fallback_calls += 1
        if use_engine != self._predict_route_last:
            self._predict_route_last = use_engine
            from .utils import log
            log.event("predict_route", engine=bool(use_engine),
                      policy=pd,
                      engine_calls=self._predict_engine_calls,
                      fallback_calls=self._predict_fallback_calls)
        if use_engine:
            eng = self._serve_engine(trees, s_iter, u_spec)
            # pred_early_stop rides the engine as a chunked early-exit
            # (ForestEngine scores freq*k-tree segments and skips the
            # rest once the whole chunk clears the margin) — same
            # reference semantics as the native walk, chunk-granular
            es = None
            if es_on:
                es = (int(opts.get("pred_early_stop_freq", 10)) * k,
                      float(opts.get("pred_early_stop_margin", 10.0)))
            if bool(opts.get("predict_sharded", False)) and not pred_leaf \
                    and es is None:
                raw = eng.predict_sharded(X)
            else:
                raw, leaves = eng.predict(X, pred_leaf=pred_leaf,
                                          early_stop=es)
                if pred_leaf:
                    return leaves
        else:
            # flattened-forest cache for the native predictor (rebuilt when
            # the model mutates or the tree horizon changes)
            flat = None
            if trees and native_available():
                key = (len(trees), k, s_iter, self._model_gen)
                if self._flat_cache is not None \
                        and self._flat_cache[0] == key:
                    flat = self._flat_cache[1]
                else:
                    flat = flatten_forest(trees, k)
                    self._flat_cache = (key, flat)
            if pred_leaf:
                out = _native_predict(trees, X, k, pred_leaf=True, flat=flat)
                if out is not None:
                    return out.astype(np.int32)
                return predict_raw_values(trees, X, leaf_index=True)
            if pred_contrib:
                from .ops.shap import predict_contrib
                return predict_contrib(trees, X, k)
            # prediction early stopping (reference
            # prediction_early_stop.cpp): enabled via params/kwargs,
            # classification objectives only, and the margin test fires at
            # ITERATION boundaries (k trees each)
            es_freq = int(opts.get("pred_early_stop_freq", 10)) * k
            es_margin = float(opts.get("pred_early_stop_margin", 10.0))
            raw = _native_predict(trees, X, k, flat=flat,
                                  es_freq=es_freq if es_on else 0,
                                  es_margin=es_margin)
            if raw is None:
                if es_on:
                    raw = _early_stop_predict_py(trees, X, k, es_freq,
                                                 es_margin)
                else:
                    raw = np.zeros((n, k), np.float64)
                    for cls in range(k):
                        cls_trees = [t for i, t in enumerate(trees)
                                     if i % k == cls]
                        raw[:, cls] = predict_raw_values(cls_trees, X)
        if self._is_average_output():
            raw = raw / max(1, len(trees) // k)
        objective = self._objective_for_predict()
        if not raw_score and objective is not None:
            if k > 1 and objective.name == "multiclass":
                conv = objective.convert_output(raw)
            else:
                conv = np.stack([objective.convert_output(raw[:, c])
                                 for c in range(k)], axis=1)
        else:
            conv = raw
        return conv[:, 0] if k == 1 else conv

    def _serve_engine(self, trees, s_iter: int, u_spec: int):
        """Cached serve/ForestEngine per (start, horizon) slice. The
        engine checks its tree-id prefix on reuse, so trees appended by
        `update()` stack incrementally instead of re-uploading the whole
        forest; any other model mutation restacks from scratch."""
        key = (s_iter, u_spec)
        eng = self._engine_cache.get(key)
        if eng is None:
            from .serve import ForestEngine
            eng = ForestEngine(trees, num_class=self.num_tree_per_iteration)
            if len(self._engine_cache) >= 8:
                self._engine_cache.pop(next(iter(self._engine_cache)))
            self._engine_cache[key] = eng
        else:
            eng.update(trees)
        return eng

    def _is_average_output(self) -> bool:
        if self._loaded is not None:
            return bool(self._loaded.get("average_output"))
        return self._cfg.boosting == "rf"

    def _objective_for_predict(self):
        try:
            if self._gbdt is not None:
                return self._gbdt.objective
            return create_objective(self._cfg)
        except Exception:
            return None

    # ------------------------------------------------------------------
    def model_to_string(self, num_iteration: int = -1) -> str:
        if self._gbdt is not None:
            ds = self._gbdt.train_data
            obj = self._gbdt.objective
            obj_str = self._objective_string(obj)
            out = save_model_to_string(
                self._gbdt.materialized_models(), self._cfg,
                self.num_tree_per_iteration,
                ds.num_total_features - 1, ds.feature_names,
                _feature_infos(ds.mappers), num_iteration, obj_str)
        else:
            # loaded model: re-serialize
            fn = self._loaded.get("feature_names") or []
            out = save_model_to_string(
                self._loaded["trees"], self._cfg,
                self._loaded["num_tree_per_iteration"],
                self._loaded.get("max_feature_idx", max(len(fn) - 1, 0)),
                fn, self._loaded.get("feature_infos"), num_iteration,
                self._loaded.get("objective", ""))
        # reference stores the pandas category lists as a model trailer
        # (python-package basic.py) so predict-time frames stay aligned
        if self.pandas_categorical:
            try:
                out += "\npandas_categorical:" + json.dumps(
                    self.pandas_categorical) + "\n"
            except TypeError:
                pass
        return out

    @staticmethod
    def _objective_string(obj) -> str:
        if obj is None:
            return ""
        extras = {
            "binary": lambda o: f" sigmoid:{o.cfg.sigmoid}",
            "multiclass": lambda o: f" num_class:{o.num_class}",
            "multiclassova": lambda o:
                f" num_class:{o.num_class} sigmoid:{o.cfg.sigmoid}",
            "lambdarank": lambda o: "",
        }
        return obj.name + extras.get(obj.name, lambda o: "")(obj)

    def save_model(self, filename: str, num_iteration: int = -1) -> "Booster":
        from .io.file_io import open_file
        with open_file(filename, "w") as fh:
            fh.write(self.model_to_string(num_iteration))
        return self

    def dump_model(self, num_iteration: int = -1) -> dict:
        if self._gbdt is not None:
            ds = self._gbdt.train_data
            return dump_model_json(
                self._gbdt.materialized_models(), self._cfg,
                self.num_tree_per_iteration,
                ds.num_total_features - 1, ds.feature_names, num_iteration,
                self._objective_string(self._gbdt.objective))
        fn = self._loaded.get("feature_names") or []
        return dump_model_json(
            self._loaded["trees"], self._cfg,
            self._loaded["num_tree_per_iteration"],
            self._loaded.get("max_feature_idx", max(len(fn) - 1, 0)),
            fn, num_iteration, self._loaded.get("objective", ""))

    # ------------------------------------------------------------------
    def feature_importance(self, importance_type: str = "split",
                           iteration: int = -1) -> np.ndarray:
        """reference Booster.feature_importance (basic.py:2410+)."""
        if self._gbdt is not None:
            nf = self._gbdt.train_data.num_total_features
        else:
            nf = self._loaded.get("max_feature_idx", 0) + 1
        imp = np.zeros(nf)
        trees = self.trees
        if iteration and iteration > 0:
            trees = trees[:iteration * self.num_tree_per_iteration]
        for t in trees:
            for node in range(t.num_leaves - 1):
                f = t.split_feature[node]
                if importance_type == "split":
                    imp[f] += 1
                else:
                    imp[f] += t.split_gain[node]
        return imp

    def feature_name(self) -> List[str]:
        if self._gbdt is not None:
            return list(self._gbdt.train_data.feature_names)
        return list(self._loaded.get("feature_names") or [])

    def get_split_value_histogram(self, feature, bins=None,
                                  xgboost_style: bool = False):
        """Histogram of a feature's numerical split thresholds across all
        trees (reference Booster.get_split_value_histogram,
        basic.py:2470+). Returns (hist, bin_edges) or, with xgboost_style,
        a pandas DataFrame / ndarray of (SplitValue, Count)."""
        if isinstance(feature, str):
            names = self.feature_name()
            if feature not in names:
                raise LightGBMError(f"Unknown feature name {feature}")
            feature = names.index(feature)
        values = []
        for t in self.trees:
            for node in range(max(t.num_leaves - 1, 0)):
                if t.split_feature[node] == feature \
                        and not t.node_is_categorical(node):
                    values.append(float(t.threshold[node]))
        values = np.asarray(values, np.float64)
        if bins is None or (isinstance(bins, int)
                            and bins > len(np.unique(values))):
            bins = max(len(np.unique(values)), 1)
        hist, bin_edges = np.histogram(values, bins=bins)
        if xgboost_style:
            ret = np.column_stack((bin_edges[1:], hist))
            ret = ret[ret[:, 1] > 0]
            try:
                import pandas as pd
                return pd.DataFrame(ret, columns=["SplitValue", "Count"])
            except ImportError:
                return ret
        return hist, bin_edges

    def free_dataset(self) -> "Booster":
        return self

    def free_network(self) -> "Booster":
        return self

    def set_network(self, machines, local_listen_port=12400,
                    listen_time_out=120, num_machines=1) -> "Booster":
        # TPU build: collectives ride the jax.sharding mesh, not sockets
        # (reference basic.py:1737; network seam = parallel/ learners)
        import warnings
        warnings.warn(
            "set_network is a no-op on the TPU build: distribution is "
            "configured by tree_learner=data/feature/voting over the "
            "jax.sharding mesh (machines/ports do not apply)",
            stacklevel=2)
        return self

    def __copy__(self):
        return self.__deepcopy__(None)

    def __deepcopy__(self, memo):
        import copy as _copy
        clone = Booster(model_str=self.model_to_string())
        clone.best_iteration = self.best_iteration
        clone.best_score = _copy.deepcopy(self.best_score, memo)
        clone.params = _copy.deepcopy(self.params, memo)
        clone.name_train_set = self.name_train_set
        return clone

    def __getstate__(self):
        # only the model string plus plain-data attributes cross the
        # pickle boundary — the parked telemetry ledger handle
        # (self._telemetry) holds open file state and stays behind
        return {"model_str": self.model_to_string(),
                "best_iteration": self.best_iteration,
                "best_score": self.best_score,
                "params": self.params,
                "name_train_set": self.name_train_set}

    def __setstate__(self, state):
        self.__init__(model_str=state["model_str"])
        self.best_iteration = state["best_iteration"]
        self.best_score = state["best_score"]
        self.params = state.get("params", {})
        self.name_train_set = state.get("name_train_set", "training")
