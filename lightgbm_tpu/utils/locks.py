"""Runtime lock-discipline assertions (`tpu_debug_locks`).

graftlint's LGT004 checker enforces lock discipline STATICALLY: an
attribute declared ``# guarded-by: _lock`` on its initializing
assignment may only be mutated inside ``with self._lock``. This module
is the dynamic twin for the cases lexical analysis can't see — calls
through aliases, discipline violated only on a rare thread interleaving
— used by the slow-gated serving concurrency stress test.

Zero overhead when off: ``guarded`` only records the class and parses
its ``guarded-by`` annotations (import-time, one regex pass over the
class source). The checking ``__setattr__`` is installed ON the class
only when ``set_debug_locks(True)`` runs (or the ``LGBT_DEBUG_LOCKS``
environment variable is set), and uninstalled on disable, so production
attribute writes stay C-speed slot/dict stores.

What the runtime mode checks: REBINDING of a guarded attribute
(``self._closed = True``) outside its lock. Container mutation through a
held reference (``self._entries[k] = v``) does not pass through
``__setattr__`` — that shape is LGT004's static job. Lock ownership is
read via the lock's own ``_is_owned()`` (RLock and Condition both carry
one); plain ``threading.Lock`` has no owner concept and degrades to
``locked()`` (held by *someone*), which is still enough to catch the
fully-unlocked mutation the stress test injects.

Violations are RECORDED, not raised, by default (a raise inside a
daemon flusher thread would be swallowed and the test would pass
vacuously); ``violations()`` / ``assert_clean()`` are the test seam.
"""
from __future__ import annotations

import inspect
import os
import re
import threading
from typing import Any, Dict, List, Tuple, Type

__all__ = ["guarded", "set_debug_locks", "debug_locks_enabled",
           "violations", "clear_violations", "assert_clean",
           "guard_map_for"]

_GUARD_RE = re.compile(
    r"self\.(_\w+)\s*(?::[^=#\n]+)?=[^#\n]*#\s*guarded-by:\s*(_\w+)")

_enabled = False
_registered: List[Type] = []                 # classes seen by @guarded
_guard_maps: Dict[Type, Dict[str, str]] = {}  # cls -> {attr: lockattr}
_violations: List[str] = []
_viol_lock = threading.Lock()


def _parse_guard_map(cls: Type) -> Dict[str, str]:
    """{attr: lockattr} from the class's `# guarded-by:` annotations.
    Source unavailable (frozen app, REPL class) -> empty map: the mode
    degrades to a no-op for that class rather than failing."""
    try:
        src = inspect.getsource(cls)
    except (OSError, TypeError):
        return {}
    return {m.group(1): m.group(2) for m in _GUARD_RE.finditer(src)}


def guarded(cls: Type) -> Type:
    """Class decorator: register `cls` for the debug-lock mode. Free
    when the mode is off — no wrapper, no metaclass, the class object
    is returned unchanged."""
    _guard_maps[cls] = _parse_guard_map(cls)
    _registered.append(cls)
    if _enabled:
        _install(cls)
    return cls


def guard_map_for(cls: Type) -> Dict[str, str]:
    """The parsed {attr: lockattr} map (tests + lint cross-checks)."""
    return dict(_guard_maps.get(cls, {}))


def _is_held(lock: Any) -> bool:
    own = getattr(lock, "_is_owned", None)
    if own is not None:
        try:
            return bool(own())
        except Exception:
            return True
    locked = getattr(lock, "locked", None)
    if locked is not None:
        try:
            return bool(locked())
        except Exception:
            return True
    return True          # unknown lock type: never false-positive


def _record(msg: str) -> None:
    with _viol_lock:
        _violations.append(msg)


def _install(cls: Type) -> None:
    if "__lgbt_plain_setattr__" in cls.__dict__:
        return
    guard = _guard_maps.get(cls, {})
    plain = cls.__setattr__

    def _checked_setattr(self, name, value,
                         _guard=guard, _plain=plain, _cls=cls):
        lockattr = _guard.get(name)
        # first binding (during __init__) is exempt: the object is not
        # shared yet and the lock itself may not exist
        if lockattr is not None and hasattr(self, name):
            lock = getattr(self, lockattr, None)
            if lock is not None and not _is_held(lock):
                _record(f"{_cls.__name__}.{name} rebound outside "
                        f"`with self.{lockattr}` "
                        f"(thread {threading.current_thread().name})")
        _plain(self, name, value)

    cls.__lgbt_plain_setattr__ = plain
    cls.__setattr__ = _checked_setattr


def _uninstall(cls: Type) -> None:
    plain = cls.__dict__.get("__lgbt_plain_setattr__")
    if plain is None:
        return
    if plain is object.__setattr__:
        # the class never defined its own __setattr__: delete ours so
        # attribute stores go back through the C slot
        del cls.__setattr__
    else:
        cls.__setattr__ = plain
    del cls.__lgbt_plain_setattr__


def set_debug_locks(on: bool) -> None:
    """Install (True) or remove (False) the checking __setattr__ on
    every @guarded class. Idempotent."""
    global _enabled
    _enabled = bool(on)
    for cls in _registered:
        (_install if _enabled else _uninstall)(cls)


def debug_locks_enabled() -> bool:
    return _enabled


def violations() -> List[str]:
    with _viol_lock:
        return list(_violations)


def clear_violations() -> None:
    with _viol_lock:
        _violations.clear()


def assert_clean() -> None:
    got = violations()
    assert not got, "lock-discipline violations:\n  " + "\n  ".join(got)


if os.environ.get("LGBT_DEBUG_LOCKS", "").strip().lower() in (
        "1", "on", "true", "yes"):
    set_debug_locks(True)
