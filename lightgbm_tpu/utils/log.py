"""Leveled logging (reference `utils/log.h:37-48` + the verbosity mapping
in `config.cpp:184-192`): Fatal raises, Warning/Info/Debug print subject
to the level, and a host-language callback can capture output (the
reference's C API installs one so logs flow to Python/R).
"""
from __future__ import annotations

import json
import sys
from typing import Any, Callable, Dict, Optional

FATAL, WARNING, INFO, DEBUG = -1, 0, 1, 2

_level = INFO
_callback: Optional[Callable[[str], None]] = None


def set_verbosity(verbosity: int) -> None:
    """config `verbosity` -> level (reference config.cpp:184-192):
    <0 fatal only, 0 warnings, 1 info, >1 debug."""
    global _level
    if verbosity < 0:
        _level = FATAL
    elif verbosity == 0:
        _level = WARNING
    elif verbosity == 1:
        _level = INFO
    else:
        _level = DEBUG


def register_callback(fn: Optional[Callable[[str], None]]) -> None:
    """Route log lines to `fn` instead of stderr (reference
    `LGBM_RegisterLogCallback`)."""
    global _callback
    _callback = fn


def _emit(tag: str, msg: str) -> None:
    line = f"[LightGBM-TPU] [{tag}] {msg}"
    if _callback is not None:
        _callback(line)
    else:
        print(line, file=sys.stderr, flush=True)


def debug(msg: str) -> None:
    if _level >= DEBUG:
        _emit("Debug", msg)


def info(msg: str) -> None:
    if _level >= INFO:
        _emit("Info", msg)


def warning(msg: str) -> None:
    if _level >= WARNING:
        _emit("Warning", msg)


def fatal(msg: str) -> None:
    """Always raises (reference Log::Fatal throws)."""
    _emit("Fatal", msg)
    raise RuntimeError(msg)


_EVENT_PREFIX = "[LightGBM-TPU] [Event] "


_validate_kind: Optional[Callable[[str], Optional[str]]] = None


def _check_kind(kind: str) -> None:
    """Assert `kind` is catalogued in obs/events.py. Import is lazy (log
    loads before the obs package) and failures to import never block an
    emit — the catalog is a debug net, not a runtime dependency."""
    global _validate_kind
    if _validate_kind is None:
        try:
            from ..obs.events import validate_kind
        except ImportError:
            return
        _validate_kind = validate_kind
    why = _validate_kind(kind)
    assert why is None, why


_tee = None   # resolved lazily to obs.trace (False when unimportable)


def _maybe_tee(kind: str, fields: Dict[str, Any]) -> None:
    """Mirror the event into the span tracer's events-<pid>.jsonl when
    a file-backed trace is live, stamping a monotonic t0 — the
    timeline's join channel for otherwise-clockless events. Runs BEFORE
    the verbosity gate: a quiet run still gets a complete timeline."""
    global _tee
    if _tee is None:
        try:
            from ..obs import trace as _obs_trace
        except ImportError:
            _tee = False
            return
        _tee = _obs_trace
    if _tee is not False and _tee.enabled():
        _tee.tee_event(kind, fields)


def event(kind: str, **fields: Any) -> None:
    """Structured channel: one machine-parseable JSON record through the
    same callback seam as the human lines (INFO level, so `verbosity=0`
    silences events exactly like info text). Human-facing lines stay
    unchanged — events are ADDITIONAL `[Event]`-tagged lines that
    `parse_event` round-trips. Kinds come from the closed catalog in
    obs/events.py (asserted under ``__debug__``; graftlint's LGT005
    enforces the same at lint time)."""
    if __debug__:
        _check_kind(kind)
    _maybe_tee(kind, fields)
    if _level >= INFO:
        rec = {"event": kind}
        rec.update(fields)
        _emit("Event", json.dumps(rec, sort_keys=True, default=str))


def parse_event(line: str) -> Optional[Dict[str, Any]]:
    """Inverse of `event`: the record dict for an `[Event]` line, None
    for any other line (including malformed event payloads)."""
    if not line.startswith(_EVENT_PREFIX):
        return None
    try:
        rec = json.loads(line[len(_EVENT_PREFIX):])
    except ValueError:
        return None
    return rec if isinstance(rec, dict) else None
