"""tpu-gbdt: a TPU-native gradient-boosting framework with the capabilities
of LightGBM 2.2.4 (reference layout: `python-package/lightgbm/__init__.py`).

Compute path is JAX/XLA/Pallas: the binned dataset lives in HBM, per-leaf
histograms are built by MXU one-hot contractions / Pallas kernels, split
finding is a vectorized scan over bins, and the distributed tree learners run
XLA collectives over a `jax.sharding.Mesh`.
"""
from .config import Config
from .io.dataset import Dataset as _RawDataset

__version__ = "0.1.0"

__all__ = [
    "Config",
]
