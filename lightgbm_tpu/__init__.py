"""tpu-gbdt: a TPU-native gradient-boosting framework with the capabilities
of LightGBM 2.2.4 (reference layout: `python-package/lightgbm/__init__.py`).

Compute path is JAX/XLA/Pallas: the binned dataset lives in HBM, per-leaf
histograms are built by MXU one-hot contractions / Pallas kernels, split
finding is a vectorized scan over bins, and the distributed tree learners run
XLA collectives over a `jax.sharding.Mesh`.
"""
from .basic import Booster, Dataset, LightGBMError
from .callback import (EarlyStopException, early_stopping, log_telemetry,
                       print_evaluation, record_evaluation, reset_parameter)
from .config import Config
from .engine import cv, train, train_many

__version__ = "2.2.4"  # capability parity target (reference VERSION.txt)

__all__ = [
    "Dataset", "Booster", "Config", "LightGBMError",
    "train", "cv", "train_many",
    "early_stopping", "print_evaluation", "record_evaluation",
    "reset_parameter", "log_telemetry", "EarlyStopException",
]

try:  # sklearn API is optional (mirrors the reference's compat gating)
    from .sklearn import (LGBMClassifier, LGBMModel, LGBMRanker,
                          LGBMRegressor)
    __all__ += ["LGBMModel", "LGBMClassifier", "LGBMRegressor", "LGBMRanker"]
except ImportError:  # pragma: no cover
    pass

try:  # plotting is optional (matplotlib / graphviz)
    from .plotting import (create_tree_digraph, plot_importance, plot_metric,
                           plot_split_value_histogram, plot_tree)
    __all__ += ["plot_importance", "plot_split_value_histogram",
                "plot_metric", "plot_tree", "create_tree_digraph"]
except ImportError:  # pragma: no cover
    pass
