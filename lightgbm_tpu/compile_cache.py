"""Process-wide XLA program registry and persistent-compile-cache wiring.

Training used to build its jitted programs per ``Booster`` instance: every
``DeviceTreeLearner`` / ``AlignedEngine`` held its own dict of
``jax.jit`` wrappers, so a second model trained on the same shapes paid
the full trace + XLA-compile bill again.  jax's trace cache is keyed on
the *function object*, and a fresh closure per instance is a fresh
function object — a cache that can never hit across instances.

This module fixes that at two levels:

* ``program(key, factory)`` — a process-wide registry of jitted
  programs.  ``key`` must capture everything the factory closure bakes
  into the trace (shapes, static ints, config scalars, and fingerprints
  of any *data* arrays the closure captures).  Two engines with equal
  keys share one jitted callable and therefore one trace per input
  shape.
* ``init_persistent_cache(path)`` — one-shot wiring of jax's on-disk
  compilation cache so a fresh *process* also skips XLA compilation.
  Exposed to users via the ``tpu_compile_cache_dir`` parameter
  (see ``config.py``); ``bench.py`` goes through the same entry point.

``note_trace()`` / ``trace_count()`` implement the compile-count
regression contract: every registered program body bumps the counter
when its Python source actually runs (i.e. once per jax trace), so a
test can train twice at the same shape and assert the second run
performed zero traces.  This mirrors ``serve.ForestEngine.compile_count``.
"""
from __future__ import annotations

import contextlib
import hashlib
import os
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

_lock = threading.Lock()
_programs: Dict[Any, Callable] = {}
_trace_count = 0
_tls = threading.local()          # per-thread attribution tag
_arg_capture = False              # profiler opt-in (enable_arg_capture)
_captured: Dict[Any, Dict[str, Any]] = {}


def note_trace() -> None:
    """Record one jax trace. Call at the top of every registered program
    body — the Python body runs once per trace, never on cache hits."""
    global _trace_count
    _trace_count += 1


def trace_count() -> int:
    return _trace_count


def program_tag(key: Any) -> str:
    """Short human-stable tag for a registry key: its leading name (when
    the key is the conventional ("name", ...) tuple) plus a digest of
    the full shape/config signature. This is what a persistent-cache
    MISS event carries — enough to say WHICH program at WHICH traced
    signature recompiled (the 552 s warm-up attribution question)."""
    name = "program"
    if isinstance(key, tuple) and key and isinstance(key[0], str):
        name = key[0]
    digest = hashlib.sha1(repr(key).encode()).hexdigest()[:10]
    return f"{name}:{digest}"


def current_attribution() -> Optional[str]:
    """The program tag (or explicit `attribution` label) active on this
    thread — what a compile-cache miss fired now would be blamed on."""
    return getattr(_tls, "tag", None)


@contextlib.contextmanager
def attribution(tag: str):
    """Label compiles dispatched inside the block (for paths that do not
    go through `program()`, e.g. the serve engine's bucket programs or
    a bench stage)."""
    prev = getattr(_tls, "tag", None)
    _tls.tag = tag
    try:
        yield
    finally:
        _tls.tag = prev


def enable_arg_capture() -> None:
    """Start recording, for every registered program, the abstract
    shapes of its call args (as ``jax.ShapeDtypeStruct`` — never live
    buffers) plus per-key call counts and host dispatch wall. The
    profiler (obs/profiler.py) flips this on at construction so
    ``collect_program_costs`` can later ``fn.lower(*specs)`` and read
    XLA ``cost_analysis()`` without holding inputs alive. Off (the
    default) the dispatch wrapper pays one module-global bool check."""
    global _arg_capture
    _arg_capture = True


def arg_capture_enabled() -> bool:
    return _arg_capture


def captured_programs() -> Dict[Any, Dict[str, Any]]:
    """key -> {tag, fn, spec_args, spec_kwargs, calls, dispatch_ms}
    for every program dispatched since ``enable_arg_capture``."""
    return dict(_captured)


def clear_captured() -> None:
    global _arg_capture
    with _lock:
        _captured.clear()
        _arg_capture = False


def _abstract_spec(x: Any) -> Any:
    """Array-likes become ShapeDtypeStruct (drops the buffer); statics
    (ints, HashableFn, ...) pass through — `lower` needs them as-is."""
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is None or dtype is None:
        return x
    import jax
    return jax.ShapeDtypeStruct(tuple(shape), dtype)


def _attributed(key: Any, fn: Callable) -> Callable:
    """Wrap a registered program so any compile its dispatch triggers is
    attributed to its registry key (one thread-local store per call;
    the jit trace cache keys on `fn`, which stays stable inside). With
    arg capture on (profiler), the first call per key also stashes the
    args' abstract specs and every call accumulates count + dispatch
    wall."""
    tag = program_tag(key)

    def run(*args, **kwargs):
        prev = getattr(_tls, "tag", None)
        _tls.tag = tag
        if _arg_capture:
            import time
            ent = _captured.get(key)
            if ent is None:
                try:
                    ent = {"tag": tag, "fn": fn,
                           "spec_args": tuple(_abstract_spec(a)
                                              for a in args),
                           "spec_kwargs": {k: _abstract_spec(v)
                                           for k, v in kwargs.items()},
                           "calls": 0, "dispatch_ms": 0.0}
                    _captured[key] = ent
                except Exception:  # noqa: BLE001 — capture is advisory
                    ent = None
            t0 = time.perf_counter()
            try:
                return fn(*args, **kwargs)
            finally:
                if ent is not None:
                    ent["calls"] += 1
                    ent["dispatch_ms"] += \
                        (time.perf_counter() - t0) * 1e3
                _tls.tag = prev
        try:
            return fn(*args, **kwargs)
        finally:
            _tls.tag = prev
    run.__wrapped__ = fn
    return run


def program(key: Any, factory: Callable[[], Callable]) -> Callable:
    """Return the process-wide jitted program for ``key``, building it
    via ``factory()`` on first use. ``key`` must be hashable and must
    cover every value the factory's closure bakes into the trace."""
    fn = _programs.get(key)
    if fn is None:
        with _lock:
            fn = _programs.get(key)
            if fn is None:
                fn = _attributed(key, factory())
                _programs[key] = fn
    return fn


def registry_size() -> int:
    return len(_programs)


def registered_program_tags() -> List[str]:
    """Tags of every registered program (miss-attribution surface: the
    fleet's sweep_round programs show up here next to the sequential
    ones, so a registry dump names what traced)."""
    with _lock:
        return sorted(program_tag(k) for k in _programs)


def clear_programs() -> None:
    """Drop every registered program (tests only — releases the device
    buffers captured by program closures)."""
    with _lock:
        _programs.clear()


def array_fingerprint(*arrays) -> str:
    """Stable content hash of host/device arrays, for registry keys.

    Program closures legitimately capture data-derived device arrays
    (bin meta tables, objective label/weight buffers). Sharing such a
    program between models is only sound when that captured data is
    identical, so the registry key carries a digest of it.
    """
    h = hashlib.blake2b(digest_size=16)
    for a in arrays:
        if a is None:
            h.update(b"\x00none")
            continue
        a = np.ascontiguousarray(np.asarray(a))
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()


def config_signature(cfg) -> Tuple:
    """Hashable snapshot of every Config field (program closures read
    hyperparameters freely, so the whole config is part of the key)."""
    import dataclasses

    items = []
    for f in dataclasses.fields(cfg):
        v = getattr(cfg, f.name)
        if isinstance(v, (list, tuple)):
            v = tuple(v) if all(
                isinstance(x, (int, float, str, bool, type(None)))
                for x in v) else repr(v)
        elif not isinstance(v, (int, float, str, bool, type(None))):
            v = repr(v)
        items.append((f.name, v))
    return tuple(items)


class HashableFn:
    """Wrap a callable so it hashes/compares by an explicit signature.

    ``move_pass`` / ``slot_hist_pass`` take the point-gradient callback
    as a *static* jit argument; jax keys the trace cache on its hash.
    Objectives hand out a fresh closure per instance, so without this
    wrapper every new Booster forced a retrace of the module-level
    kernels even though the closures compute the same function.
    """

    __slots__ = ("fn", "sig")

    def __init__(self, fn: Callable, sig: Any):
        self.fn = fn
        self.sig = ("HashableFn", sig)

    def __call__(self, *args, **kwargs):
        return self.fn(*args, **kwargs)

    def __hash__(self):
        return hash(self.sig)

    def __eq__(self, other):
        return isinstance(other, HashableFn) and self.sig == other.sig

    def __repr__(self):  # keeps jax debug names stable across instances
        return f"HashableFn({self.sig!r})"


_persistent_cache_dir: Optional[str] = None
_pcache_hits = 0
_pcache_misses = 0
_miss_by_program: Dict[str, int] = {}
_hooks_installed = False


def persistent_cache_events() -> Dict[str, int]:
    """Counts of persistent-compile-cache hits/misses observed by the
    jax hooks this process (zeros until `install_cache_event_hooks`)."""
    return {"hits": _pcache_hits, "misses": _pcache_misses}


def miss_attribution() -> Dict[str, int]:
    """Persistent-cache miss counts keyed by the attribution tag active
    when each miss fired (`program_tag` for registry programs, explicit
    `attribution()` labels elsewhere, "unattributed" when none). This is
    the aggregate the CLI folds into trace_summary.json — the per-event
    stream already lands on the structured log channel."""
    return dict(_miss_by_program)


def note_persistent_cache_miss(module_name: str, cache_key: str = "") -> None:
    """Record one persistent-cache miss: bump the counter and emit a
    structured `[Event]` carrying the XLA module name, the cache key,
    and the traced program signature active on this thread — the data
    needed to explain a long warm-up DESPITE compile_cache_hit=true
    (which only says the cache directory was non-empty, not that every
    program hit)."""
    global _pcache_misses
    _pcache_misses += 1
    tag = current_attribution() or "unattributed"
    _miss_by_program[tag] = _miss_by_program.get(tag, 0) + 1
    from .utils import log
    log.event("compile_cache_miss", module=str(module_name),
              key=str(cache_key)[:20], program=current_attribution())


def _note_persistent_cache_hit(module_name: str, cache_key: str = "") -> None:
    global _pcache_hits
    _pcache_hits += 1


def install_cache_event_hooks() -> bool:
    """Wrap jax's persistent-cache logging seam
    (`jax._src.compiler.log_persistent_cache_{miss,hit}` — called
    exactly once per compile on the miss/hit path) so every miss lands
    on the structured log channel with program attribution. Idempotent;
    returns False when this jax build lacks the seam (counters then stay
    zero — callers treat that as "no data", not an error)."""
    global _hooks_installed
    if _hooks_installed:
        return True
    try:
        from jax._src import compiler as _jax_compiler
        orig_miss = _jax_compiler.log_persistent_cache_miss
        orig_hit = _jax_compiler.log_persistent_cache_hit
    except (ImportError, AttributeError):
        return False

    def miss(module_name, cache_key, *a, **kw):
        note_persistent_cache_miss(getattr(module_name, "name",
                                           module_name), cache_key)
        return orig_miss(module_name, cache_key, *a, **kw)

    def hit(module_name, cache_key, *a, **kw):
        _note_persistent_cache_hit(getattr(module_name, "name",
                                           module_name), cache_key)
        return orig_hit(module_name, cache_key, *a, **kw)

    _jax_compiler.log_persistent_cache_miss = miss
    _jax_compiler.log_persistent_cache_hit = hit
    _hooks_installed = True
    return True


def persistent_cache_dir() -> Optional[str]:
    return _persistent_cache_dir


def cache_dir_entries(path: Optional[str]) -> int:
    """Count cache files currently in a compilation-cache directory."""
    if not path or not os.path.isdir(path):
        return 0
    n = 0
    for _root, _dirs, files in os.walk(path):
        n += len(files)
    return n


def init_persistent_cache(path: str) -> str:
    """Point jax's persistent compilation cache at ``path`` (one-shot).

    The earlier bench-only wiring missed for two reasons: it kept the
    default ``min_compile_time_secs`` floor of 2 s (the round loop is
    dozens of sub-2 s programs — none were written), and on non-TPU
    backends jax additionally requires the XLA-client caches to be
    opted in before anything persists. Both are forced here, and the
    setup runs before the first trace because ``Config.update`` calls
    it when ``tpu_compile_cache_dir`` is parsed.

    Idempotent: the first directory wins for the process lifetime
    (jax's cache config cannot be swapped once populated).
    """
    global _persistent_cache_dir
    if _persistent_cache_dir is not None:
        return _persistent_cache_dir
    path = os.path.abspath(os.path.expanduser(path))
    os.makedirs(path, exist_ok=True)

    import jax

    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    for opt, val in (
        ("jax_persistent_cache_min_entry_size_bytes", 0),
        # Required for cache hits on the CPU backend; harmless on TPU.
        ("jax_persistent_cache_enable_xla_caches", "all"),
    ):
        try:
            jax.config.update(opt, val)
        except Exception:
            pass  # older jax: option absent, dir + floor still apply
    try:
        from jax.experimental.compilation_cache import compilation_cache
        compilation_cache.set_cache_dir(path)
    except Exception:
        pass
    install_cache_event_hooks()
    _persistent_cache_dir = path
    return path
