"""Training entry points: `train` and `cv`.

Re-creates the reference `python-package/lightgbm/engine.py`: the per-
iteration callback loop with EarlyStopException control flow (`engine.py:
239-267`), evals_result plumbing, `init_model` continued training, and
stratified/plain k-fold `cv` (`engine.py:371+`).
"""
from __future__ import annotations

import collections
import copy
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import numpy as np

from . import callback as callback_mod
from .basic import Booster, Dataset, LightGBMError
from .callback import EarlyStopException
from .config import Config


def train(params: Dict[str, Any], train_set: Dataset,
          num_boost_round: int = 100,
          valid_sets: Optional[List[Dataset]] = None,
          valid_names: Optional[List[str]] = None,
          fobj: Optional[Callable] = None,
          feval: Optional[Callable] = None,
          init_model: Optional[Union[str, Booster]] = None,
          feature_name: Union[str, List[str]] = "auto",
          categorical_feature: Union[str, List[int]] = "auto",
          early_stopping_rounds: Optional[int] = None,
          evals_result: Optional[Dict] = None,
          verbose_eval: Union[bool, int] = True,
          learning_rates: Optional[Union[List, Callable]] = None,
          keep_training_booster: bool = False,
          callbacks: Optional[List[Callable]] = None) -> Booster:
    """reference engine.py:19-280."""
    params = dict(params)
    # num_boost_round aliases resolve through Config canonicalization
    for alias in ("num_boost_round", "num_iterations", "num_iteration",
                  "n_iter", "num_tree", "num_trees", "num_round",
                  "num_rounds", "n_estimators"):
        if alias in params:
            num_boost_round = int(params.pop(alias))
    for alias in ("early_stopping_round", "early_stopping_rounds",
                  "early_stopping"):
        if alias in params:
            v = params.pop(alias)
            early_stopping_rounds = None if v is None else int(v)
    if fobj is not None:
        params["objective"] = "none"

    if not isinstance(train_set, Dataset):
        raise TypeError("Training only accepts Dataset object")
    train_set._update_params(params)
    if feature_name != "auto":
        train_set.feature_name = feature_name
    if categorical_feature != "auto":
        train_set.categorical_feature = categorical_feature

    # continued training (engine.py:139-164)
    init_booster = None
    if isinstance(init_model, str):
        init_booster = Booster(model_file=init_model)
    elif isinstance(init_model, Booster):
        init_booster = init_model

    booster = Booster(params=params, train_set=train_set)
    # resilience (resilience/): checkpoint manager + auto-resume bundle.
    # With tpu_checkpoint_dir unset both stay None and the loop below
    # adds one None check per round — no fences, no other work
    ckpt_mgr = None
    resume_bundle = None
    _r_cfg = getattr(booster, "_cfg", None)
    if _r_cfg is not None and _r_cfg.tpu_checkpoint_dir:
        from .resilience import checkpoint as _ckpt
        from .resilience import resume as _resume
        ckpt_mgr = _ckpt.CheckpointManager.from_config(_r_cfg)
        resume_bundle = _resume.load_latest(ckpt_mgr)
    if init_booster is not None and resume_bundle is None:
        # a valid checkpoint already contains the init model's trees
        _seed_from_model(booster, init_booster)
    is_valid_contain_train = False
    train_data_name = "training"
    valid_sets = valid_sets or []
    user_named = valid_names is not None
    if valid_names is None:
        valid_names = [f"valid_{i}" for i in range(len(valid_sets))]
    reduced_valid_sets = []
    name_valid_sets = []
    for i, vs in enumerate(valid_sets):
        if vs is train_set:
            is_valid_contain_train = True
            if user_named:
                train_data_name = valid_names[i]
            continue
        vs._update_params(params)
        booster.add_valid(vs, valid_names[i])
        reduced_valid_sets.append(vs)
        name_valid_sets.append(valid_names[i])
    booster.name_train_set = train_data_name

    callbacks = list(callbacks or [])
    if early_stopping_rounds is not None and early_stopping_rounds > 0:
        callbacks.append(callback_mod.early_stopping(
            int(early_stopping_rounds),
            bool(params.get("first_metric_only", False)),
            verbose=bool(verbose_eval)))
    if isinstance(verbose_eval, bool) and verbose_eval:
        callbacks.append(callback_mod.print_evaluation())
    elif isinstance(verbose_eval, int):
        callbacks.append(callback_mod.print_evaluation(verbose_eval))
    if evals_result is not None:
        callbacks.append(callback_mod.record_evaluation(evals_result))
    if learning_rates is not None:
        callbacks.append(callback_mod.reset_parameter(
            learning_rate=learning_rates))
    telemetry = getattr(getattr(booster, "_gbdt", None), "telemetry",
                        None)
    if telemetry is not None and not any(
            getattr(cb, "order", 0) == 25 for cb in callbacks):
        # tpu_trace runs fold eval values into the ledger automatically
        callbacks.append(callback_mod.log_telemetry(period=0))
    callbacks_before = [cb for cb in callbacks
                        if getattr(cb, "before_iteration", False)]
    callbacks_after = [cb for cb in callbacks if cb not in callbacks_before]
    callbacks_before.sort(key=lambda cb: getattr(cb, "order", 0))
    callbacks_after.sort(key=lambda cb: getattr(cb, "order", 0))

    # resume after valid sets + callbacks exist: restore() overwrites the
    # replayed valid scores and rehydrates callback closures (early stop)
    start_iter = 0
    resume_warmup_s = 0.0
    if resume_bundle is not None:
        import time as _time
        _t0 = _time.perf_counter()
        start_iter = _resume.restore(booster, resume_bundle,
                                     callbacks=callbacks)
        resume_warmup_s = _time.perf_counter() - _t0
    fault_plan = getattr(getattr(booster, "_gbdt", None), "_fault_plan",
                         None)
    preempted = False
    guard = None
    if ckpt_mgr is not None:
        from .resilience.preempt import PreemptGuard
        guard = PreemptGuard()
        guard.install()

    # main loop (engine.py:239-267)
    try:
        for i in range(start_iter, num_boost_round):
            if fault_plan is not None:
                fault_plan.on_round(i)
            for cb in callbacks_before:
                cb(callback_mod.CallbackEnv(
                    model=booster, params=params, iteration=i,
                    begin_iteration=0, end_iteration=num_boost_round,
                    evaluation_result_list=None, telemetry=telemetry))
            booster.update(fobj=fobj)

            evaluation_result_list = []
            if is_valid_contain_train:
                evaluation_result_list.extend(
                    (train_data_name, m, v, b)
                    for _, m, v, b in booster.eval_train())
            if reduced_valid_sets:
                evaluation_result_list.extend(booster.eval_valid())
            if feval is not None:
                evaluation_result_list.extend(
                    _run_feval(feval, booster, train_data_name,
                               is_valid_contain_train, name_valid_sets))
            try:
                for cb in callbacks_after:
                    cb(callback_mod.CallbackEnv(
                        model=booster, params=params, iteration=i,
                        begin_iteration=0, end_iteration=num_boost_round,
                        evaluation_result_list=evaluation_result_list,
                        telemetry=telemetry))
            except EarlyStopException as es:
                booster.best_iteration = es.best_iteration + 1
                evaluation_result_list = es.best_score
                break
            if guard is not None and guard.triggered:
                # finish-in-flight semantics: round i fully committed
                # above; flush one final checkpoint and stop cleanly
                ckpt_mgr.write(booster, i + 1, callbacks=callbacks,
                               reason=guard.signal_name or "preempt")
                preempted = True
                break
            if ckpt_mgr is not None and ckpt_mgr.due(i + 1):
                ckpt_mgr.write(booster, i + 1, callbacks=callbacks,
                               reason="periodic")
    finally:
        if guard is not None:
            guard.uninstall()
    booster.best_score = collections.defaultdict(collections.OrderedDict)
    for data_name, eval_name, score, _ in (evaluation_result_list or []):
        booster.best_score[data_name][eval_name] = score
    resilience_stats = None
    if ckpt_mgr is not None or start_iter:
        resilience_stats = {"resumed_from": start_iter,
                            "resume_warmup_s": resume_warmup_s,
                            "ckpt_writes": getattr(ckpt_mgr, "writes", 0),
                            "ckpt_write_s": getattr(ckpt_mgr, "write_s",
                                                    0.0),
                            "preempted": preempted}
    booster._preempted = preempted
    booster._resilience = resilience_stats
    if not keep_training_booster:
        # round-trip through the model string (engine.py:271-272)
        fresh = Booster(model_str=booster.model_to_string())
        fresh.best_iteration = booster.best_iteration
        fresh.best_score = booster.best_score
        fresh.params = params
        # the round ledger lives on the training GBDT, which this fresh
        # booster no longer holds — carry the handle so bst.telemetry
        # still resolves after train() returns (the in-run profiler
        # rides along the same way for bst.profiler / bench / the CLI
        # trace-summary fold)
        fresh._telemetry = telemetry
        fresh._profiler = getattr(getattr(booster, "_gbdt", None),
                                  "_profiler", None)
        fresh._preempted = preempted
        fresh._resilience = resilience_stats
        return fresh
    return booster


def _seed_from_model(booster: Booster, init_booster: Booster) -> None:
    """Continued training: previous model's predictions become init scores
    (reference engine.py:158-164 / application.cpp:90-93)."""
    gbdt = booster._gbdt
    td = gbdt.train_data
    # replay loaded trees onto the training scores as init score
    from .ops.predict import TreePredictor
    trees = init_booster.trees
    if not trees:
        return
    pred = TreePredictor(trees)
    bundle = None
    if getattr(td, "bundles", None) is not None:
        import jax.numpy as _jnp
        b = td.bundles
        bundle = (_jnp.asarray(b.col), _jnp.asarray(b.off),
                  _jnp.asarray(b.packed.astype(np.int32)))
    leaves = pred.predict_binned_leaves(td.bins, bundle)
    k = gbdt.num_tree_per_iteration
    import jax.numpy as jnp
    for i, tree in enumerate(trees):
        gbdt.train_score.add_tree_by_leaves(
            leaves[i], tree.leaf_value[:tree.num_leaves], i % k)
    gbdt.train_score.has_init_score = True
    # keep the old trees in the model so the final model contains both
    gbdt.models = list(trees) + gbdt.models


def _run_feval(feval, booster: Booster, train_name: str,
               include_train: bool, valid_names: List[str]):
    out = []
    gbdt = booster._gbdt
    if include_train:
        if hasattr(gbdt, "_sync_train_score"):
            gbdt._sync_train_score()
        preds = gbdt.train_score.numpy()
        res = feval(preds[0] if preds.shape[0] == 1 else preds.T,
                    booster._train_set)
        out.extend(_norm_feval(res, train_name))
    for i, su in enumerate(gbdt.valid_scores):
        preds = su.numpy()
        pub = (booster._valid_sets_public[i]
               if i < len(booster._valid_sets_public) else None)
        res = feval(preds[0] if preds.shape[0] == 1 else preds.T, pub)
        name = valid_names[i] if i < len(valid_names) else f"valid_{i}"
        out.extend(_norm_feval(res, name))
    return out


def _norm_feval(res, data_name):
    if isinstance(res, list):
        return [(data_name, n, v, b) for n, v, b in res]
    n, v, b = res
    return [(data_name, n, v, b)]


# ---------------------------------------------------------------------------
# cross validation (reference engine.py:283-580)
# ---------------------------------------------------------------------------
class _CVBooster:
    def __init__(self) -> None:
        self.boosters: List[Booster] = []
        self.best_iteration = -1

    def append(self, booster: Booster) -> None:
        self.boosters.append(booster)

    def __getattr__(self, name):
        def handler_function(*args, **kwargs):
            return [getattr(b, name)(*args, **kwargs)
                    for b in self.boosters]
        return handler_function


def _make_n_folds(full_data: Dataset, folds, nfold: int, params: Dict,
                  seed: int, stratified: bool, shuffle: bool):
    full_data = full_data.construct()
    num_data = full_data.num_data
    if folds is not None:
        if not hasattr(folds, "__iter__") and not hasattr(folds, "split"):
            raise AttributeError("folds should be a generator or iterator")
        if hasattr(folds, "split"):
            group = full_data.get_group()
            group_info = (np.asarray(group, np.int64)
                          if group is not None else None)
            if group_info is not None:
                flatted_group = np.repeat(
                    range(len(group_info)), repeats=group_info)
            else:
                flatted_group = np.zeros(num_data, dtype=np.int64)
            folds = folds.split(X=np.zeros(num_data),
                                y=full_data.get_label(),
                                groups=flatted_group)
    else:
        group = full_data.get_group()
        if group is not None:
            # group-aware folds: split queries (engine.py:320-337)
            group = np.asarray(group, np.int64)
            num_queries = len(group)
            rng = np.random.RandomState(seed)
            q_perm = (rng.permutation(num_queries) if shuffle
                      else np.arange(num_queries))
            q_folds = np.array_split(q_perm, nfold)
            boundaries = np.concatenate([[0], np.cumsum(group)])
            folds = []
            for qf in q_folds:
                test_idx = np.concatenate(
                    [np.arange(boundaries[q], boundaries[q + 1])
                     for q in sorted(qf)]) if len(qf) else np.zeros(0, int)
                train_idx = np.setdiff1d(np.arange(num_data), test_idx)
                folds.append((train_idx, test_idx))
        elif stratified:
            y = np.asarray(full_data.get_label())
            rng = np.random.RandomState(seed)
            folds = []
            test_sets: List[List[int]] = [[] for _ in range(nfold)]
            for cls in np.unique(y):
                cls_idx = np.nonzero(y == cls)[0]
                if shuffle:
                    cls_idx = cls_idx[rng.permutation(len(cls_idx))]
                for f, chunk in enumerate(np.array_split(cls_idx, nfold)):
                    test_sets[f].extend(chunk.tolist())
            all_idx = np.arange(num_data)
            for f in range(nfold):
                te = np.sort(np.asarray(test_sets[f], np.int64))
                folds.append((np.setdiff1d(all_idx, te), te))
        else:
            rng = np.random.RandomState(seed)
            perm = (rng.permutation(num_data) if shuffle
                    else np.arange(num_data))
            chunks = np.array_split(perm, nfold)
            all_idx = np.arange(num_data)
            folds = [(np.setdiff1d(all_idx, np.sort(c)), np.sort(c))
                     for c in chunks]
    ret = []
    for train_idx, test_idx in folds:
        train_sub = full_data.subset(np.sort(np.asarray(train_idx)))
        valid_sub = full_data.subset(np.sort(np.asarray(test_idx)))
        ret.append((train_sub, valid_sub))
    return ret


def _agg_cv_result(raw_results):
    """reference engine.py:355-368."""
    cvmap = collections.OrderedDict()
    metric_type = {}
    for one_result in raw_results:
        for one_line in one_result:
            key = one_line[0] + " " + one_line[1]
            metric_type[key] = one_line[3]
            cvmap.setdefault(key, [])
            cvmap[key].append(one_line[2])
    return [("cv_agg", k, float(np.mean(v)), metric_type[k], float(np.std(v)))
            for k, v in cvmap.items()]


def cv(params: Dict[str, Any], train_set: Dataset, num_boost_round: int = 100,
       folds=None, nfold: int = 5, stratified: bool = True,
       shuffle: bool = True, metrics=None, fobj=None, feval=None,
       init_model=None, feature_name="auto", categorical_feature="auto",
       early_stopping_rounds: Optional[int] = None, fpreproc=None,
       verbose_eval=None, show_stdv: bool = True, seed: int = 0,
       callbacks=None, eval_train_metric: bool = False,
       return_cvbooster: bool = False) -> Dict[str, List[float]]:
    """reference engine.py:371-580."""
    if not isinstance(train_set, Dataset):
        raise TypeError("Training only accepts Dataset object")
    params = dict(params)
    for alias in ("num_boost_round", "num_iterations", "num_iteration",
                  "n_iter", "num_tree", "num_trees", "num_round",
                  "num_rounds", "n_estimators"):
        if alias in params:
            num_boost_round = int(params.pop(alias))
    if metrics is not None:
        params["metric"] = metrics
    cfg_obj = params.get("objective", "")
    stratified = stratified and str(cfg_obj).startswith(
        ("binary", "multiclass")) if cfg_obj else stratified

    train_set._update_params(params)
    folds_data = _make_n_folds(train_set, folds, nfold, params, seed,
                               stratified, shuffle)
    cvbooster = _CVBooster()
    fold_envs = []
    for tr, te in folds_data:
        if fpreproc is not None:
            tr, te, tparams = fpreproc(tr, te, dict(params))
        else:
            tparams = params
        bst = Booster(params=tparams, train_set=tr)
        bst.add_valid(te, "valid")
        cvbooster.append(bst)

    results = collections.defaultdict(list)
    callbacks = list(callbacks or [])
    if early_stopping_rounds is not None and early_stopping_rounds > 0:
        callbacks.append(callback_mod.early_stopping(
            int(early_stopping_rounds),
            bool(params.get("first_metric_only", False)),
            verbose=False))
    if isinstance(verbose_eval, bool) and verbose_eval:
        callbacks.append(callback_mod.print_evaluation(show_stdv=show_stdv))
    elif isinstance(verbose_eval, int):
        callbacks.append(callback_mod.print_evaluation(verbose_eval,
                                                       show_stdv))
    callbacks_before = [cb for cb in callbacks
                        if getattr(cb, "before_iteration", False)]
    callbacks_after = [cb for cb in callbacks if cb not in callbacks_before]

    for i in range(num_boost_round):
        for cb in callbacks_before:
            cb(callback_mod.CallbackEnv(
                model=cvbooster, params=params, iteration=i,
                begin_iteration=0, end_iteration=num_boost_round,
                evaluation_result_list=None))
        raw = []
        for bst in cvbooster.boosters:
            bst.update(fobj=fobj)
            one = bst.eval_valid()
            if eval_train_metric:
                one = [("train " + d, m, v, b) for d, m, v, b
                       in bst.eval_train()] + one
            if feval is not None:
                one = one + _run_feval(feval, bst, "training", False,
                                       ["valid"])
            raw.append(one)
        res = _agg_cv_result(raw)
        for _, key, mean, _, std in res:
            results[key + "-mean"].append(mean)
            results[key + "-stdv"].append(std)
        try:
            for cb in callbacks_after:
                cb(callback_mod.CallbackEnv(
                    model=cvbooster, params=params, iteration=i,
                    begin_iteration=0, end_iteration=num_boost_round,
                    evaluation_result_list=[
                        (r[0], r[1], r[2], r[3], r[4]) for r in res]))
        except EarlyStopException as es:
            cvbooster.best_iteration = es.best_iteration + 1
            for k in results:
                results[k] = results[k][:cvbooster.best_iteration]
            break
    if return_cvbooster:
        results["cvbooster"] = cvbooster
    return dict(results)


# many-model sweep training (sweep/): `train`'s fleet sibling,
# re-exported here so `from lightgbm_tpu.engine import train_many`
# mirrors `train`. Bottom-of-module import: sweep.trainer reaches back
# for _seed_from_model lazily, so this line must follow its definition.
from .sweep import train_many  # noqa: E402,F401  isort:skip
