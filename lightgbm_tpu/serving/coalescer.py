"""Request coalescer: concurrent predict calls -> full shape buckets.

`ForestEngine` pads every batch to a power-of-two bucket of at least
`min_bucket` rows (engine.py:_bucket), so a 16-row request pays the same
device time as a 256-row one. Per-request dispatch therefore wastes most
of the machine at high QPS; throughput has to come from batching. This
module is the batcher:

* `submit(model, X)` enqueues the request and returns a
  `concurrent.futures.Future` immediately — callers block only on
  `.result()`, never on each other;
* a background flusher drains each model's queue as ONE concatenated
  engine call when either (a) the queued rows reach `max_batch_rows`
  (a bucket is full — flush early, latency be damned) or (b) the oldest
  request has waited `max_batch_wait_ms` (the latency SLO — flush
  whatever we have);
* a request is never split across engine calls: batches take whole
  requests FIFO while they fit, and results are sliced back to each
  future by row offset. An oversized single request (> max_batch_rows)
  flushes alone — the engine chunks it internally.

Errors (unknown model, bad feature width) are delivered through the
future of every request in the failed batch; the flusher thread never
dies. Batch-fill accounting (`rows / padded bucket rows`) is the bench's
measure of how much of each device dispatch was real work.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Any, Dict, List, Optional

import numpy as np

from ..obs import trace as obs_trace
from ..utils import locks

__all__ = ["RequestCoalescer"]


class _Req:
    __slots__ = ("X", "rows", "t_submit", "future", "span")

    def __init__(self, X: np.ndarray) -> None:
        self.X = X
        self.rows = int(X.shape[0])
        self.t_submit = time.perf_counter()
        self.future: Future = Future()
        self.span = None        # TraceSpan when request tracing is on


@locks.guarded
class RequestCoalescer:
    """SLO-aware batcher in front of a `ModelRegistry`."""

    def __init__(self, registry, max_batch_wait_ms: float = 2.0,
                 max_batch_rows: int = 8192, tracer=None,
                 placer=None) -> None:
        self.registry = registry
        # multi-device placer (serving/frontend/placement.py): when
        # attached, each flush routes to the replica with the
        # shallowest queue instead of the entry's default engine
        self._placer = placer
        self.wait_s = max(float(max_batch_wait_ms), 0.0) / 1e3
        self.max_batch_rows = max(int(max_batch_rows), 1)
        # request tracer (obs/reqtrace.py): None when tpu_serve_trace is
        # off — the hot path then pays one is-None branch, nothing else
        self._tracer = tracer
        self._cv = threading.Condition()
        self._queues: Dict[str, deque] = {}         # guarded-by: _cv
        self._closed = False                        # guarded-by: _cv
        self.batches = 0
        self.requests = 0
        self.rows = 0
        self.padded_rows = 0            # sum of engine bucket rows dispatched
        self.flush_full = 0             # batches flushed on a full bucket
        self.flush_deadline = 0         # batches flushed on the wait SLO
        self.failures = 0               # requests completed with an exception
        # live metrics handle: resolved once, None when the plane is off
        # (submit/flush then pay one attribute check each)
        from ..obs import metrics as obs_metrics
        self._metrics = (obs_metrics.serving_instruments()
                         if obs_metrics.enabled() else None)
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="lgbt-serve-coalescer")
        self._thread.start()

    # -- client side -------------------------------------------------------
    def submit(self, model: str, X) -> Future:
        """Enqueue one predict request; the future resolves to the raw
        margins array ([n] for single-class, [n, k] otherwise)."""
        X = np.ascontiguousarray(np.asarray(X, np.float64))
        if X.ndim != 2:
            raise ValueError(f"request matrix must be 2-D, got {X.shape}")
        req = _Req(X)
        with self._cv:
            if self._closed:
                raise RuntimeError("coalescer is closed")
            self.requests += 1
            # mint the span under _cv (after the closed check) so the
            # flusher can never observe a queued request without one,
            # and a closed-coalescer raise never leaks a started span
            if self._tracer is not None:
                req.span = self._tracer.start(model, req.rows,
                                              req.t_submit)
            self._queues.setdefault(model, deque()).append(req)
            self._cv.notify()
        if self._metrics is not None:
            self._metrics.requests.inc()
        return req.future

    def close(self, drain: bool = True) -> None:
        """Stop the flusher. With drain (default) queued requests flush
        first; without, they fail with a RuntimeError."""
        with self._cv:
            if self._closed:
                return
            self._closed = True
            if not drain:
                t_now = time.perf_counter()
                for q in self._queues.values():
                    for req in q:
                        if req.span is not None:
                            # started == finished even for requests the
                            # shutdown killed — their trace row says so
                            self._tracer.finish(
                                req.span,
                                queue_wait_ms=(t_now - req.t_submit) * 1e3,
                                batch_id=None, flush_reason="closed",
                                batch_rows=None, batch_requests=None,
                                fill_ratio=None, dispatch_ms=None,
                                total_ms=(t_now - req.t_submit) * 1e3,
                                status="error", error="coalescer closed")
                        req.future.set_exception(
                            RuntimeError("coalescer closed"))
                    q.clear()
            self._cv.notify()
        self._thread.join(timeout=30)

    def __enter__(self) -> "RequestCoalescer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def stats(self) -> Dict[str, Any]:
        with self._cv:
            return {
                "batches": self.batches,
                "requests": self.requests,
                "rows": self.rows,
                "padded_rows": self.padded_rows,
                "fill_ratio": round(self.rows / self.padded_rows, 4)
                if self.padded_rows else None,
                "rows_per_batch": round(self.rows / self.batches, 1)
                if self.batches else None,
                "flush_full": self.flush_full,
                "flush_deadline": self.flush_deadline,
                "failures": self.failures,
            }

    # -- flusher thread ----------------------------------------------------
    def _take_batch(self, q: deque) -> List[_Req]:
        """Whole requests FIFO while they fit max_batch_rows; at least
        one (an oversized request flushes alone, never split)."""
        batch = [q.popleft()]
        total = batch[0].rows
        while q and total + q[0].rows <= self.max_batch_rows:
            req = q.popleft()
            total += req.rows
            batch.append(req)
        return batch

    def _loop(self) -> None:
        while True:
            with self._cv:
                now = time.perf_counter()
                ready: List = []        # (model, [reqs], reason)
                deadline_next: Optional[float] = None
                for model, q in self._queues.items():
                    while q:
                        rows = sum(r.rows for r in q)
                        due = q[0].t_submit + self.wait_s
                        if rows >= self.max_batch_rows:
                            ready.append((model, self._take_batch(q),
                                          "full"))
                            continue
                        if self._closed or due <= now:
                            ready.append((model, self._take_batch(q),
                                          "deadline"))
                            continue
                        deadline_next = (due if deadline_next is None
                                         else min(deadline_next, due))
                        break
                if not ready:
                    if self._closed:
                        return
                    timeout = (None if deadline_next is None
                               else max(deadline_next - now, 0.0))
                    self._cv.wait(timeout=timeout)
                    continue
            for model, batch, reason in ready:   # dispatch OFF the lock
                self._flush(model, batch, reason)

    def _flush(self, model: str, batch: List[_Req], reason: str) -> None:
        rows = sum(r.rows for r in batch)
        tr = self._tracer
        batch_id = tr.next_batch_id() if tr is not None else None
        t_start = time.perf_counter()   # flusher picked the batch up
        replica = None
        try:
            entry = self.registry.acquire(model)
            X = (batch[0].X if len(batch) == 1
                 else np.concatenate([r.X for r in batch], axis=0))
            eng = entry.engine
            if self._placer is not None:
                # routing failure degrades to the entry's own engine —
                # placement is an optimization, never a request killer
                try:
                    replica = self._placer.route(model, entry, rows)
                    eng = replica.engine
                except Exception:  # noqa: BLE001
                    replica = None
            t_d0 = time.perf_counter()
            try:
                with obs_trace.span("serving.flush", model=model,
                                    rows=rows, requests=len(batch),
                                    reason=reason):
                    margins, _ = eng.predict(X)
            finally:
                if replica is not None:
                    self._placer.done(replica, rows)
            t_d1 = time.perf_counter()
            padded = sum(eng._bucket(min(rows - lo, eng.chunk_rows))
                         for lo in range(0, max(rows, 1), eng.chunk_rows))
            entry.buckets.add(eng._bucket(min(rows, eng.chunk_rows)))
            if entry.num_class <= 1:
                margins = margins[:, 0]
            t_done = time.perf_counter()
            if tr is not None:
                # finish spans BEFORE resolving futures: a caller that
                # wakes on .result() must find its trace row complete
                dispatch_ms = (t_d1 - t_d0) * 1e3
                fill = rows / padded if padded else None
                for req in batch:
                    tr.finish(req.span,
                              queue_wait_ms=(t_start - req.t_submit) * 1e3,
                              batch_id=batch_id, flush_reason=reason,
                              batch_rows=rows, batch_requests=len(batch),
                              fill_ratio=fill, dispatch_ms=dispatch_ms,
                              total_ms=(t_done - req.t_submit) * 1e3)
            off = 0
            for req in batch:
                req.future.set_result(margins[off:off + req.rows])
                off += req.rows
            with self._cv:
                self.batches += 1
                self.rows += rows
                self.padded_rows += padded
                if reason == "full":
                    self.flush_full += 1
                else:
                    self.flush_deadline += 1
            m = self._metrics
            if m is not None:
                m.batches.labels(reason=reason).inc()
                m.rows.inc(rows)
                m.padded_rows.inc(padded)
                if self.padded_rows:
                    m.fill.set(self.rows / self.padded_rows)
                m.completed.labels(model=model, status="ok").inc(len(batch))
                lat = m.latency.labels(model=model)
                for req in batch:
                    lat.observe((t_done - req.t_submit) * 1e3,
                                exemplar=(req.span.trace_id
                                          if req.span is not None else None))
        except BaseException as exc:  # noqa: BLE001 — delivered via futures
            t_err = time.perf_counter()
            undone = [r for r in batch if not r.future.done()]
            with self._cv:
                self.failures += len(undone)
            m = self._metrics
            if m is not None:
                m.failures.inc(len(undone))
                # failed requests still count as completed (status=
                # "error") so completed ok+error == requests submitted
                # even under injected engine errors
                m.completed.labels(model=model,
                                   status="error").inc(len(undone))
                done_n = len(batch) - len(undone)
                if done_n:
                    m.completed.labels(model=model,
                                       status="ok").inc(done_n)
            if tr is not None:
                err = f"{type(exc).__name__}: {exc}"
                for req in batch:
                    # status guard: a span already finished on the
                    # success path (failure mid-resolution) stays ok
                    if req.span is not None and req.span.status == "pending":
                        tr.finish(req.span,
                                  queue_wait_ms=(t_start - req.t_submit)
                                  * 1e3,
                                  batch_id=batch_id, flush_reason=reason,
                                  batch_rows=rows,
                                  batch_requests=len(batch),
                                  fill_ratio=None, dispatch_ms=None,
                                  total_ms=(t_err - req.t_submit) * 1e3,
                                  status="error", error=err)
            for req in undone:
                req.future.set_exception(exc)
