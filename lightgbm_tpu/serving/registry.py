"""Multi-tenant model registry: many named boosters resident on device.

The reference stops at one `Predictor` per process (`src/application/
predictor.hpp`); production traffic wants N models hot at once, which on
a TPU means N device-resident stacked forests competing for HBM. The
registry owns that pool:

* each entry wraps one `serve.ForestEngine` (mode="raw") built straight
  from model text — or from a `resilience/` checkpoint directory, read
  ONLY through the MANIFEST.json pointer so a concurrent trainer
  mid-write can never hand us a torn model (see `load`);
* byte accounting comes from `ForestEngine.device_bytes()` (the stacked
  device arrays), and an HBM budget (`tpu_serve_hbm_budget_mb`) evicts
  least-recently-*used* entries until the pool fits — the entry being
  loaded is never the victim, and an oversized single model loads with
  a warning rather than failing (the budget shapes eviction, it is not
  an admission gate);
* `swap()` replaces an entry atomically under the registry lock. The
  old engine object stays alive for as long as any in-flight request
  holds it (plain refcounting — `acquire()` hands out the entry, the
  request keeps scoring on it even if a swap lands mid-flight), so a
  hot-swap never fails or blocks a request.

Every load/evict/swap emits a structured `log.event` and, when a ledger
is attached, a `note` record — the same channel training uses, so a
serving host's timeline reads like a training run's.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional

from ..models.model_text import load_model_from_string
from ..resilience.checkpoint import read_manifest
from ..serve.engine import ForestEngine
from ..utils import locks, log

__all__ = ["ModelEntry", "ModelRegistry", "load_checkpoint_model_text"]


def load_checkpoint_model_text(directory: str):
    """(model_text, version) from a resilience/ checkpoint directory, or
    None while the directory is unreadable.

    Reads ONLY via the MANIFEST.json pointer — never by globbing
    `ckpt_*` (a concurrent trainer stages tmp dirs and retention deletes
    old ones; directory listings are exactly the torn state the atomic
    manifest exists to hide). A mid-write manifest (`read_manifest`
    returns None) or a checkpoint dir swept by retention between the
    pointer read and the file read both return None: the caller retries
    on its next poll instead of crashing.
    """
    man = read_manifest(directory)
    if man is None:
        return None
    latest = str(man.get("latest") or "")
    if not latest:
        return None
    path = os.path.join(directory, latest, "model.txt")
    try:
        with open(path) as fh:
            return fh.read(), latest
    except OSError:
        return None


class ModelEntry:
    """One resident model: its engine plus accounting the registry needs."""

    __slots__ = ("name", "engine", "num_class", "num_features", "bytes",
                 "version", "source", "loaded_at", "hits", "buckets",
                 "compact", "aot_buckets")

    def __init__(self, name: str, engine: ForestEngine, num_class: int,
                 num_features: int, version: str, source: str) -> None:
        self.name = name
        self.engine = engine
        self.num_class = num_class
        self.num_features = num_features
        self.bytes = engine.device_bytes()
        self.version = version
        self.source = source
        self.loaded_at = time.time()
        self.hits = 0
        self.buckets: set = set()
        self.compact = engine.compact    # plan actually in effect
        self.aot_buckets = 0             # AOT shape buckets attached

    def warm(self, rows: int) -> None:
        """Trace + compile the engine's program for the pow2 bucket that
        `rows` lands in, so the first real request finds a hot cache.
        Also records the bucket so a replacement engine can pre-warm the
        same working set before a swap."""
        import numpy as np
        rows = max(int(rows), 1)
        X = np.zeros((min(rows, self.engine.chunk_rows),
                      self.num_features), np.float64)
        self.engine.predict(X)
        self.buckets.add(self.engine._bucket(X.shape[0]))


@locks.guarded
class ModelRegistry:
    """Named ForestEngine pool with HBM-budget LRU eviction."""

    def __init__(self, hbm_budget_mb: float = 0.0, warm_rows: int = 256,
                 ledger=None, tracer=None, compact: str = "off",
                 compact_tol: float = 0.05, aot_dir: str = "") -> None:
        self.hbm_budget_bytes = int(max(float(hbm_budget_mb), 0.0) * 2**20)
        self.warm_rows = int(warm_rows)
        self.ledger = ledger
        # compact residency plan (tpu_serve_compact) applied to every
        # load, behind the parity gate; aot_dir (tpu_serve_aot_dir)
        # points at serve/aot.py artifacts attached at load time
        self.compact = compact
        self.compact_tol = float(compact_tol)
        self.aot_dir = aot_dir
        # request tracer (obs/reqtrace.py): load/swap/evict notes also
        # land as MARKER rows in its ring so /debug/requests interleaves
        # registry churn with the requests it slowed down
        self._tracer = tracer
        self._lock = threading.RLock()
        self._entries: Dict[str, ModelEntry] = {}   # guarded-by: _lock
        self._tick = 0      # guarded-by: _lock (monotone LRU clock)
        self._last_used: Dict[str, int] = {}        # guarded-by: _lock
        self.loads = 0
        self.swaps = 0
        self.evictions = 0
        self.evicted: List[str] = []        # eviction order, oldest first
        # live metrics plane: the pool is an AGGREGATE HBM owner (its
        # engines each have their own serve/forest row — summing both
        # would double count), and load/swap/evict feed counters when
        # the plane is on (resolved once here, None otherwise)
        from ..obs import memory as obs_memory
        from ..obs import metrics as obs_metrics
        obs_memory.track("serving/registry_pool", self,
                         lambda r: r.total_bytes(), aggregate=True)
        self._metrics = (obs_metrics.serving_instruments()
                         if obs_metrics.enabled() else None)

    # -- notes -------------------------------------------------------------
    def _note(self, kind: str, **fields) -> None:
        """One load/swap/evict note. Callers pass the FULL literal event
        kind (catalogued in obs/events.py) so lint and grep both see it;
        runtime validation in log.event covers this pass-through."""
        log.event(kind, **fields)  # graftlint: disable=LGT005 kinds are caller literals, validated at runtime
        if self.ledger is not None:
            self.ledger.commit(dict({"kind": "note", "note": kind},
                                    **fields))
        if self._tracer is not None:
            # marker row only (no second log.event) — the tracer lock is
            # a leaf below self._lock, safe to take here
            self._tracer.note(kind, **fields)

    # -- building ----------------------------------------------------------
    def _compact_parity(self, engine: ForestEngine, trees, k: int,
                        nfeat: int):
        """(abs_err, rel_err) of the compact engine vs the f64 host
        oracle over a deterministic probe batch whose rows span each
        feature's split-threshold range (random WITHIN the ranges, never
        pinned exactly AT a threshold — quantization legitimately moves
        the decision boundary by <= half a step; the gate measures margin
        drift, not boundary placement)."""
        import numpy as np

        from ..ops.predict import predict_raw_values
        lo = np.full(nfeat, np.inf)
        hi = np.full(nfeat, -np.inf)
        for t in trees:
            if t.num_leaves <= 1:
                continue
            dt = np.asarray(t.decision_type, np.int32)
            num = (dt & 1) == 0
            sf = np.asarray(t.split_feature)[num]
            th = np.asarray(t.threshold, np.float64)[num]
            np.minimum.at(lo, sf, th)
            np.maximum.at(hi, sf, th)
        unused = ~np.isfinite(lo)
        lo[unused] = 0.0
        hi[unused] = 1.0
        span = np.maximum(hi - lo, 1.0)
        rng = np.random.RandomState(0)
        X = (lo + (hi - lo) * rng.rand(128, nfeat)
             + (rng.rand(128, nfeat) - 0.5) * 0.25 * span)
        oracle = np.stack([predict_raw_values(trees[c::k], X)
                           for c in range(k)], axis=1)
        got, _ = engine.predict(X)
        err = float(np.max(np.abs(got - oracle)))
        return err, err / max(1.0, float(np.max(np.abs(oracle))))

    def _attach_aot(self, engine: ForestEngine, name: str,
                    nfeat: int) -> int:
        """Attach AOT artifact buckets when tpu_serve_aot_dir is set:
        a per-model subdirectory (`<aot_dir>/<name>/`) wins over a shared
        single-model artifact at the directory root."""
        if not self.aot_dir:
            return 0
        from ..serve import aot
        sub = os.path.join(self.aot_dir, name)
        d = (sub if os.path.isfile(os.path.join(sub,
                                                aot.ARTIFACT_MANIFEST))
             else self.aot_dir)
        return aot.load_artifact(engine, d, nfeat, model=name)

    def _build_entry(self, name: str, model_str: str, version: str,
                     source: str, warm_rows: Optional[int]) -> ModelEntry:
        loaded = load_model_from_string(model_str)
        trees = loaded["trees"]
        if not trees:
            raise ValueError(f"model {name!r} ({source}) has no trees")
        k = int(loaded.get("num_tree_per_iteration", 1))
        nfeat = int(loaded.get("max_feature_idx", -1)) + 1
        if nfeat <= 0:
            nfeat = int(max(t.split_feature.max() if t.num_leaves > 1 else 0
                            for t in trees)) + 1
        engine = ForestEngine(trees, num_class=k, mode="raw",
                              compact=self.compact)
        if self.compact != "off":
            err, rel = self._compact_parity(engine, trees, k, nfeat)
            if rel > self.compact_tol:
                # parity gate failed: keep correctness, lose density —
                # the f32 engine replaces the compact one and the
                # structured event says exactly why
                self._note("serve_compact_fallback", model=name,
                           plan=self.compact, err=err, rel_err=rel,
                           tol=self.compact_tol)
                engine = ForestEngine(trees, num_class=k, mode="raw")
            else:
                self._note("serve_compact", model=name, plan=self.compact,
                           err=err, rel_err=rel,
                           bytes=engine.device_bytes(),
                           f32_bytes=engine.f32_device_bytes())
        aot_n = self._attach_aot(engine, name, nfeat)
        entry = ModelEntry(name, engine, k, nfeat, version, source)
        entry.aot_buckets = aot_n
        rows = self.warm_rows if warm_rows is None else int(warm_rows)
        if rows > 0:
            entry.warm(rows)
        return entry

    # -- public API --------------------------------------------------------
    def load(self, name: str, model_str: Optional[str] = None,
             model_file: Optional[str] = None,
             checkpoint_dir: Optional[str] = None,
             warm_rows: Optional[int] = None,
             version: str = "direct") -> ModelEntry:
        """Load (or replace) a named model from exactly one of: a model
        text string, a model file path, or a resilience/ checkpoint
        directory (resolved through its manifest pointer)."""
        srcs = [s for s in (model_str, model_file, checkpoint_dir)
                if s is not None]
        if len(srcs) != 1:
            raise ValueError("load() takes exactly one of model_str / "
                             "model_file / checkpoint_dir")
        if model_file is not None:
            with open(model_file) as fh:
                model_str = fh.read()
            source = model_file
        elif checkpoint_dir is not None:
            got = load_checkpoint_model_text(checkpoint_dir)
            if got is None:
                raise FileNotFoundError(
                    f"no readable checkpoint manifest under {checkpoint_dir}")
            model_str, version = got
            source = checkpoint_dir
        else:
            source = "model_str"
        entry = self._build_entry(name, model_str, version, source,
                                  warm_rows)
        with self._lock:
            replacing = name in self._entries
            self._entries[name] = entry
            self._touch(name)
            self.loads += 1
            if self._metrics is not None:
                self._metrics.loads.inc()
            self._note("serve_load", model=name, version=version,
                       source=source, bytes=entry.bytes,
                       trees=entry.engine.num_trees, replaced=replacing)
            self._evict_over_budget(protect=name)
        return entry

    def swap(self, name: str, model_str: str, version: str = "direct",
             source: str = "swap",
             warm_rows: Optional[int] = None) -> ModelEntry:
        """Zero-downtime replacement: build + warm the new engine OFF the
        lock (no request blocks on its compiles), then atomically
        install it. The displaced engine keeps serving any request that
        already acquired it."""
        old = self.get(name)
        entry = self._build_entry(name, model_str, version, source,
                                  warm_rows)
        # pre-warm the buckets live traffic actually used, so the first
        # post-swap request at those shapes hits a compiled program
        if old is not None:
            import numpy as np
            for b in sorted(old.buckets - entry.buckets):
                entry.engine.predict(
                    np.zeros((min(b, entry.engine.chunk_rows),
                              entry.num_features), np.float64))
                entry.buckets.add(b)
        with self._lock:
            self._entries[name] = entry
            self._touch(name)
            self.swaps += 1
            if self._metrics is not None:
                self._metrics.swaps.inc()
            self._note("serve_swap", model=name, version=version,
                       source=source, bytes=entry.bytes,
                       trees=entry.engine.num_trees,
                       old_version=old.version if old is not None else None)
            self._evict_over_budget(protect=name)
        return entry

    def acquire(self, name: str) -> ModelEntry:
        """The entry for `name` (bumps its LRU clock). KeyError when the
        model is absent — loaded never, or evicted."""
        with self._lock:
            entry = self._entries.get(name)
            if entry is None:
                raise KeyError(f"model {name!r} not resident "
                               f"(loaded={sorted(self._entries)})")
            entry.hits += 1
            self._touch(name)
            return entry

    def get(self, name: str) -> Optional[ModelEntry]:
        with self._lock:
            return self._entries.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._entries)

    def total_bytes(self) -> int:
        with self._lock:
            return sum(e.bytes for e in self._entries.values())

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "models": {n: {"bytes": e.bytes, "version": e.version,
                               "hits": e.hits,
                               "trees": e.engine.num_trees,
                               "compile_count": e.engine.compile_count,
                               "cache_hits": e.engine.cache_hits,
                               "predict_calls": e.engine.predict_calls,
                               "compact": e.compact,
                               "aot_buckets": e.aot_buckets,
                               "aot_hits": e.engine.aot_hits,
                               "early_stop_exits":
                                   e.engine.early_stop_exits}
                           for n, e in self._entries.items()},
                "total_bytes": sum(e.bytes
                                   for e in self._entries.values()),
                "hbm_budget_bytes": self.hbm_budget_bytes,
                "loads": self.loads,
                "swaps": self.swaps,
                "evictions": self.evictions,
                "evicted": list(self.evicted),
            }

    def aot_compact_stats(self) -> Dict[str, Any]:
        """Per-model AOT + compaction detail for the metrics exporter's
        /metrics.json `serving` block: artifact hit state and the bytes
        a compact plan saved vs its f32 counterfactual."""
        with self._lock:
            out: Dict[str, Any] = {}
            for n, e in self._entries.items():
                f32_bytes = e.engine.f32_device_bytes()
                out[n] = {
                    "aot": {"buckets": e.aot_buckets,
                            "hits": e.engine.aot_hits,
                            "source": e.engine.aot_source},
                    "compact": {"plan": e.compact, "bytes": e.bytes,
                                "f32_bytes": f32_bytes,
                                "bytes_saved": max(f32_bytes - e.bytes,
                                                   0)},
                }
            return out

    # -- eviction ----------------------------------------------------------
    def _touch(self, name: str) -> None:  # guarded-by: caller
        self._tick += 1
        self._last_used[name] = self._tick

    def _evict_over_budget(self, protect: str) -> None:  # guarded-by: caller
        """Caller holds the lock. Evict LRU entries until the pool fits
        the budget; `protect` (the entry just installed) is exempt."""
        if self.hbm_budget_bytes <= 0:
            return
        total = sum(e.bytes for e in self._entries.values())
        while total > self.hbm_budget_bytes:
            victims = [n for n in self._entries if n != protect]
            if not victims:
                log.event("serve_over_budget", model=protect,
                          bytes=total, budget=self.hbm_budget_bytes)
                return
            victim = min(victims, key=lambda n: self._last_used[n])
            gone = self._entries.pop(victim)
            self._last_used.pop(victim, None)
            total -= gone.bytes
            self.evictions += 1
            if self._metrics is not None:
                self._metrics.evictions.inc()
            self.evicted.append(victim)
            self._note("serve_evict", model=victim, version=gone.version,
                       bytes=gone.bytes, total_bytes=total,
                       budget=self.hbm_budget_bytes)
