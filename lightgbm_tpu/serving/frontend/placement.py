"""Multi-device model placement: HBM-headroom assignment, hot-model
replication, shallowest-queue routing.

The registry (serving/registry.py) treats the accelerator as one pool:
every ForestEngine lands on the default device and one global budget
drives LRU eviction. A multi-chip serving host wastes N-1 devices that
way. The `Placer` turns the pool per-device:

* **assignment** — each loaded model's forest is pinned
  (`ForestEngine.to_device`) on the device with the most HBM headroom:
  real backend `memory_stats()` where the platform reports them, else
  the configured per-device budget minus the bytes this placer already
  placed (the emulated-device / CPU case, where `obs/memory`'s
  accountant has no per-device counters to offer);
* **replication** — models are request-rate ranked; the hottest get
  replicas (engine clones pinned to other devices, warmed off the
  routing path) up to `tpu_serve_replicas`, filling free headroom only
  — a copy is an optimization and never evicts someone else's primary;
* **routing** — the coalescer asks `route()` per batch and gets the
  replica with the shallowest queue (pending rows), so a slow device
  backs itself off; per-device depth is exported as the
  `serve_device_queue_rows{device}` gauge;
* **per-device LRU budget** — `tpu_serve_hbm_budget_mb` becomes a
  per-device ceiling: placing a primary on a full device evicts that
  device's least-recently-routed replicas (`serve_place` events with
  reason="evict"), never the whole-registry LRU sweep. The service
  disables the registry's global budget when a placer is attached so
  the two policies cannot fight.

A hot swap replaces the registry entry's engine object; `route()`
detects the stale replica set by identity and re-places lazily — no
watcher integration needed, the first post-swap batch repins.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from ...utils import locks, log

__all__ = ["Placer", "Replica", "resolve_devices"]

# routing calls between hot-model replication checks: rare enough to
# stay off the hot path, frequent enough that a traffic shift
# replicates within a few hundred batches
_REBALANCE_EVERY = 64
# request-rate counters are halved this often (in routing calls) so the
# "hot" ranking tracks current traffic, not lifetime totals
_RATE_DECAY_EVERY = 1024


def resolve_devices(count: int) -> list:
    """The device list `tpu_serve_devices` names: 0 = all visible,
    N = the first N (clamped)."""
    import jax
    devs = list(jax.devices())
    if count > 0:
        devs = devs[:count]
    return devs


class Replica:
    """One device-resident copy of a model's forest."""

    __slots__ = ("model", "engine", "device_index", "bytes",
                 "pending_rows", "primary")

    def __init__(self, model: str, engine, device_index: int,
                 primary: bool) -> None:
        self.model = model
        self.engine = engine
        self.device_index = device_index
        self.bytes = int(engine.device_bytes())
        self.pending_rows = 0
        self.primary = primary


@locks.guarded
class Placer:
    """Per-device replica pool over the registry's entries."""

    def __init__(self, registry, devices: Optional[list] = None,
                 budget_mb: float = 0.0, max_replicas: int = 2,
                 warm_rows: int = 256, tracer=None) -> None:
        self.registry = registry
        self.devices = list(devices if devices is not None
                            else resolve_devices(0))
        self.budget_bytes = int(max(float(budget_mb), 0.0) * 2 ** 20)
        self.max_replicas = max(int(max_replicas), 1)
        self.warm_rows = int(warm_rows)
        self._tracer = tracer
        self._lock = threading.RLock()
        self._replicas: Dict[str, List[Replica]] = {}   # guarded-by: _lock
        # the entry engine each model's replica set was derived from;
        # a swap installs a new engine object and the identity mismatch
        # triggers lazy re-placement on the next route
        self._src: Dict[str, Any] = {}                  # guarded-by: _lock
        self._rate: Dict[str, int] = {}                 # guarded-by: _lock
        self._routes = 0                                # guarded-by: _lock
        self._tick = 0                                  # guarded-by: _lock
        self._last_used: Dict[tuple, int] = {}          # guarded-by: _lock
        # (model, device) pairs that already announced serve_route
        self._routed_pairs: set = set()                 # guarded-by: _lock
        self._replicating: set = set()                  # guarded-by: _lock
        self.placements = 0
        self.replications = 0
        self.evictions = 0
        from ...obs import metrics as obs_metrics
        self._metrics = (obs_metrics.serving_instruments()
                         if obs_metrics.enabled() else None)

    # -- notes -------------------------------------------------------------
    def _note(self, kind: str, **fields) -> None:
        log.event(kind, **fields)  # graftlint: disable=LGT005 kinds are caller literals, validated at runtime
        if self._tracer is not None:
            self._tracer.note(kind, **fields)

    # -- accounting --------------------------------------------------------
    def _used_bytes(self, dev_i: int) -> int:  # guarded-by: caller
        return sum(r.bytes for reps in self._replicas.values()
                   for r in reps if r.device_index == dev_i)

    def _headroom(self, dev_i: int) -> float:  # guarded-by: caller
        """Free HBM on a device: real backend stats when the platform
        reports them, else the configured budget minus placed bytes,
        else placed bytes negated (pure load balancing)."""
        if self.budget_bytes > 0:
            return float(self.budget_bytes - self._used_bytes(dev_i))
        try:
            stats = self.devices[dev_i].memory_stats()
        except Exception:
            stats = None
        if stats and "bytes_limit" in stats:
            return float(stats["bytes_limit"]
                         - stats.get("bytes_in_use", 0))
        return -float(self._used_bytes(dev_i))

    def _touch(self, model: str, dev_i: int) -> None:  # guarded-by: caller
        self._tick += 1
        self._last_used[(model, dev_i)] = self._tick

    def _gauge_depth(self, dev_i: int) -> None:  # guarded-by: caller
        if self._metrics is not None:
            depth = sum(r.pending_rows
                        for reps in self._replicas.values()
                        for r in reps if r.device_index == dev_i)
            self._metrics.device_queue.labels(device=str(dev_i)).set(depth)

    # -- placement ---------------------------------------------------------
    def _evict_for(self, dev_i: int, need: int,
                   protect: str) -> None:  # guarded-by: caller
        """Per-device LRU: drop least-recently-routed replicas on
        `dev_i` until `need` bytes fit the budget; `protect`'s replicas
        are exempt. Over-budget with nothing evictable degrades to the
        registry's serve_over_budget discipline: place anyway, warn."""
        if self.budget_bytes <= 0:
            return
        while self._used_bytes(dev_i) + need > self.budget_bytes:
            victims = [r for reps in self._replicas.values() for r in reps
                       if r.device_index == dev_i and r.model != protect]
            if not victims:
                log.event("serve_over_budget", model=protect, bytes=need,
                          budget=self.budget_bytes, device=dev_i)
                return
            victim = min(victims, key=lambda r: self._last_used.get(
                (r.model, r.device_index), 0))
            self._drop(victim, reason="evict")
            self.evictions += 1

    def _drop(self, rep: Replica, reason: str) -> None:  # guarded-by: caller
        reps = self._replicas.get(rep.model, [])
        if rep in reps:
            reps.remove(rep)
        if not reps:
            self._replicas.pop(rep.model, None)
            self._src.pop(rep.model, None)
        self._last_used.pop((rep.model, rep.device_index), None)
        self._routed_pairs.discard((rep.model, rep.device_index))
        self._note("serve_place", model=rep.model,
                   device=rep.device_index, bytes=rep.bytes,
                   reason=reason, replicas=len(reps))
        if self._metrics is not None:
            self._metrics.replicas.labels(model=rep.model).set(len(reps))
        self._gauge_depth(rep.device_index)

    def place(self, name: str, entry) -> Replica:
        """Pin a (re)loaded entry's engine on the device with the most
        headroom; replaces any existing replica set for the name."""
        with self._lock:
            for rep in list(self._replicas.get(name, [])):
                self._drop(rep, reason="replace")
            need = int(entry.engine.device_bytes())
            dev_i = max(range(len(self.devices)),
                        key=lambda i: (self._headroom(i), -i))
            self._evict_for(dev_i, need, protect=name)
            if len(self.devices) > 1:
                entry.engine.to_device(self.devices[dev_i])
            rep = Replica(name, entry.engine, dev_i, primary=True)
            self._replicas[name] = [rep]
            self._src[name] = entry.engine
            self._touch(name, dev_i)
            self.placements += 1
            self._note("serve_place", model=name, device=dev_i,
                       bytes=rep.bytes, reason="load", replicas=1)
            if self._metrics is not None:
                self._metrics.replicas.labels(model=name).set(1)
            # a hosting device exposes its queue gauge from placement
            # on (depth 0), not from its first routed batch
            self._gauge_depth(dev_i)
            return rep

    # -- replication -------------------------------------------------------
    def _clone_engine(self, entry, device):
        """A second engine over the same trees, pinned to `device` and
        warmed there. Built OFF the placer lock — compiles must not
        stall routing."""
        from ...serve.engine import ForestEngine
        import numpy as np
        src = entry.engine
        eng = ForestEngine(src.trees, num_class=entry.num_class,
                           mode=src.mode, compact=src.compact)
        eng.to_device(device)
        rows = min(max(self.warm_rows, 1), eng.chunk_rows)
        eng.predict(np.zeros((rows, entry.num_features), np.float64))
        return eng

    def _replicate(self, name: str) -> None:
        """Add one replica of `name` on the best device not already
        hosting it, headroom permitting. Runs on a short-lived daemon
        thread; `_replicating` keeps it single-flight per model."""
        try:
            entry = self.registry.get(name)
            with self._lock:
                reps = self._replicas.get(name)
                if (entry is None or reps is None
                        or self._src.get(name) is not entry.engine
                        or len(reps) >= self.max_replicas):
                    return
                hosted = {r.device_index for r in reps}
                free = [i for i in range(len(self.devices))
                        if i not in hosted]
                need = int(entry.engine.device_bytes())
                free = [i for i in free
                        if self.budget_bytes <= 0
                        or self._used_bytes(i) + need <= self.budget_bytes]
                if not free:
                    return
                dev_i = max(free, key=lambda i: (self._headroom(i), -i))
            eng = self._clone_engine(entry, self.devices[dev_i])
            with self._lock:
                reps = self._replicas.get(name)
                if reps is None or self._src.get(name) is not entry.engine:
                    return      # swapped/evicted while we compiled
                rep = Replica(name, eng, dev_i, primary=False)
                reps.append(rep)
                self._touch(name, dev_i)
                self.replications += 1
                self._note("serve_place", model=name, device=dev_i,
                           bytes=rep.bytes, reason="replicate",
                           replicas=len(reps))
                if self._metrics is not None:
                    self._metrics.replicas.labels(
                        model=name).set(len(reps))
                self._gauge_depth(dev_i)
        finally:
            with self._lock:
                self._replicating.discard(name)

    def _maybe_replicate(self) -> None:  # guarded-by: caller
        """Kick async replication for the hottest under-replicated
        model (request-rate ranked)."""
        if len(self.devices) < 2 or self.max_replicas < 2:
            return
        for name, _n in sorted(self._rate.items(),
                               key=lambda kv: -kv[1]):
            reps = self._replicas.get(name)
            if (reps is None or len(reps) >= self.max_replicas
                    or name in self._replicating):
                continue
            self._replicating.add(name)
            threading.Thread(target=self._replicate, args=(name,),
                             daemon=True,
                             name=f"lgbt-serve-replicate-{name}").start()
            return

    def rebalance(self) -> None:
        """Force one replication check synchronously (tests and the
        bench call this instead of waiting for the route-count
        trigger); any spawned clone still builds on its own thread."""
        with self._lock:
            self._maybe_replicate()

    # -- routing -----------------------------------------------------------
    def route(self, name: str, entry, rows: int) -> Replica:
        """The replica this batch should run on: shallowest pending-row
        queue, ties to the lower device. Re-places lazily after a swap
        (new engine object) and on first sight of a model the service
        never announced."""
        with self._lock:
            reps = self._replicas.get(name)
            if reps is None or self._src.get(name) is not entry.engine:
                # first sight or post-swap: (re)place under the same
                # lock hold so a concurrent eviction can't race the
                # fresh set away before we pick from it
                self.place(name, entry)
                reps = self._replicas[name]
            rep = min(reps, key=lambda r: (r.pending_rows, r.device_index))
            rep.pending_rows += rows
            self._rate[name] = self._rate.get(name, 0) + 1
            self._routes += 1
            self._touch(name, rep.device_index)
            pair = (name, rep.device_index)
            if pair not in self._routed_pairs:
                self._routed_pairs.add(pair)
                self._note("serve_route", model=name,
                           device=rep.device_index,
                           primary=rep.primary, replicas=len(reps))
            if self._routes % _RATE_DECAY_EVERY == 0:
                for k in list(self._rate):
                    self._rate[k] //= 2
            if self._routes % _REBALANCE_EVERY == 0:
                self._maybe_replicate()
            self._gauge_depth(rep.device_index)
            return rep

    def done(self, rep: Replica, rows: int) -> None:
        """Batch finished on `rep`: release its queue depth."""
        with self._lock:
            rep.pending_rows = max(rep.pending_rows - rows, 0)
            self._gauge_depth(rep.device_index)

    # -- views -------------------------------------------------------------
    def replica_count(self, name: str) -> int:
        with self._lock:
            return len(self._replicas.get(name, []))

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "devices": len(self.devices),
                "budget_bytes_per_device": self.budget_bytes,
                "placements": self.placements,
                "replications": self.replications,
                "evictions": self.evictions,
                "models": {
                    n: [{"device": r.device_index, "bytes": r.bytes,
                         "pending_rows": r.pending_rows,
                         "primary": r.primary} for r in reps]
                    for n, reps in self._replicas.items()},
                "device_used_bytes": {
                    str(i): self._used_bytes(i)
                    for i in range(len(self.devices))},
                "device_queue_rows": {
                    str(i): sum(r.pending_rows
                                for reps in self._replicas.values()
                                for r in reps if r.device_index == i)
                    for i in range(len(self.devices))},
            }
