"""ScoringFrontend: the serving plane's network front door.

The exporter (serving/exporter.py) proved the shape — a stdlib
`ThreadingHTTPServer` (no new dependencies), one bound handler class
per instance, `port=0` for an OS-assigned ephemeral port. This module
extends that pattern from scrape-only to the scoring path itself:

* ``POST /v1/score/<model>`` — score rows. Two body encodings:
  - JSON (``Content-Type: application/json``): ``{"rows": [[...],
    ...]}`` or a bare list-of-lists;
  - packed binary (``Content-Type: application/octet-stream``):
    row-major little-endian floats, ``X-Num-Features`` required,
    ``X-Dtype: f32|f64`` (default f32) — the zero-copy path for fat
    clients.
  Optional ``X-Deadline-Ms`` bounds the request end to end: expired in
  the admission queue -> 504 without an engine dispatch. The response
  is JSON (``{"model", "rows", "predictions"}``) unless the client
  sends ``Accept: application/octet-stream`` (f32 LE bytes + an
  ``X-Shape`` header).
* ``GET /healthz`` — readiness document: resident models, device and
  replica counts, QoS map, currently-shedding models. Schema-checked
  by CI.

Status mapping is the admission layer's policy surface: 400 malformed
(validated HERE — a bad body never reaches the coalescer), 404 unknown
model, 429 shed (``Retry-After: 1``; counted in
``serve_shed_total{model,qos}``), 504 deadline expired, 503 shutting
down, 500 engine error. Handler threads block on the request future —
the coalescer's batching, the placer's routing, and the tracer's spans
all behave exactly as for in-process callers.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ...utils import log
from .qos import QOS_NAMES, DeadlineExpired, ShedError

__all__ = ["ScoringFrontend"]

# request-body ceiling: 256 MiB of f64 rows is far beyond any sane
# request and cheap insurance against a runaway client
_MAX_BODY = 256 << 20
_SCORE_PREFIX = "/v1/score/"
# how long a handler thread waits on the admission future when the
# client sent no deadline of its own
_DEFAULT_WAIT_S = 60.0


class _BadRequest(ValueError):
    """Parse/validation failure -> 400; never reaches the coalescer."""


def _parse_json_rows(body: bytes) -> np.ndarray:
    try:
        doc = json.loads(body)
    except Exception as exc:
        raise _BadRequest(f"body is not valid JSON: {exc}") from None
    rows = doc.get("rows") if isinstance(doc, dict) else doc
    if not isinstance(rows, list) or not rows:
        raise _BadRequest("need a non-empty 'rows' list of feature rows")
    try:
        X = np.asarray(rows, np.float64)
    except Exception:
        raise _BadRequest("rows are not numeric or not rectangular") \
            from None
    if X.ndim != 2 or X.shape[1] == 0:
        raise _BadRequest(
            f"rows must be 2-D [n, num_features], got shape {X.shape}")
    return X


def _parse_binary_rows(body: bytes, headers) -> np.ndarray:
    feats = headers.get("X-Num-Features")
    if not feats or not feats.isdigit() or int(feats) == 0:
        raise _BadRequest(
            "packed-binary bodies need X-Num-Features: <positive int>")
    nfeat = int(feats)
    dt = (headers.get("X-Dtype") or "f32").strip().lower()
    if dt not in ("f32", "f64"):
        raise _BadRequest(f"X-Dtype must be f32 or f64, got {dt!r}")
    itemsize = 4 if dt == "f32" else 8
    if not body or len(body) % (itemsize * nfeat) != 0:
        raise _BadRequest(
            f"body length {len(body)} is not a whole number of "
            f"{nfeat}-feature {dt} rows")
    flat = np.frombuffer(body, dtype=("<f4" if dt == "f32" else "<f8"))
    return flat.reshape(-1, nfeat).astype(np.float64)


class _Handler(BaseHTTPRequestHandler):
    frontend: "ScoringFrontend" = None  # set per server instance
    protocol_version = "HTTP/1.1"       # keep-alive: bench clients reuse

    # -- plumbing ----------------------------------------------------------
    def _reply(self, code: int, body: bytes, ctype: str,
               extra: Optional[Dict[str, str]] = None) -> None:
        # count BEFORE the body goes out: a client that has read the
        # response must already see it in requests_by_code/metrics
        self.frontend._count(code)
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (extra or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _reply_json(self, code: int, doc: Dict[str, Any],
                    extra: Optional[Dict[str, str]] = None) -> None:
        self._reply(code, json.dumps(doc, sort_keys=True,
                                     default=str).encode(),
                    "application/json", extra)

    def log_message(self, fmt, *args):  # silence per-request stderr noise
        pass

    # -- GET ---------------------------------------------------------------
    def do_GET(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler API
        path = self.path.split("?", 1)[0]
        try:
            if path in ("/", "/healthz"):
                self._reply_json(200, self.frontend.render_healthz())
            else:
                self._reply_json(404, {"error": "not found",
                                       "path": path})
        except Exception as exc:  # noqa: BLE001 — a broken view != dead server
            try:
                self._reply_json(500, {"error": str(exc)[:200]})
            except Exception:
                pass

    # -- POST --------------------------------------------------------------
    def do_POST(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler API
        path = self.path.split("?", 1)[0]
        try:
            if not path.startswith(_SCORE_PREFIX):
                self._reply_json(404, {"error": "not found", "path": path})
                return
            model = path[len(_SCORE_PREFIX):].strip("/")
            code, doc, raw, extra = self.frontend.score(
                model, self.headers, self._read_body())
            if raw is not None:
                self._reply(code, raw, "application/octet-stream", extra)
            else:
                self._reply_json(code, doc, extra)
        except _BadRequest as exc:
            self._reply_json(400, {"error": str(exc)})
        except Exception as exc:  # noqa: BLE001 — a broken request != dead server
            try:
                self._reply_json(500, {"error": str(exc)[:200]})
            except Exception:
                pass

    def _read_body(self) -> bytes:
        try:
            n = int(self.headers.get("Content-Length") or 0)
        except ValueError:
            raise _BadRequest("bad Content-Length") from None
        if n <= 0:
            raise _BadRequest("empty request body")
        if n > _MAX_BODY:
            raise _BadRequest(f"body over the {_MAX_BODY} byte cap")
        return self.rfile.read(n)


class ScoringFrontend:
    """HTTP scoring endpoint over a ServingService's admission plane."""

    def __init__(self, service, port: int = 0,
                 host: str = "127.0.0.1") -> None:
        self.service = service
        self.admission = service.admission
        if self.admission is None:
            raise ValueError(
                "ScoringFrontend needs the service's admission "
                "controller (built when tpu_serve_port or tpu_serve_qos "
                "is set)")
        handler = type("_BoundHandler", (_Handler,), {"frontend": self})
        # stock TCPServer listens with backlog 5 — a thundering herd of
        # fresh client connections (the bench and CI overload legs open
        # dozens at once) gets connection resets at accept time
        server_cls = type("_FrontServer", (ThreadingHTTPServer,),
                          {"daemon_threads": True,
                           "request_queue_size": 128})
        self._server = server_cls((host, int(port)), handler)
        self.host = host
        self.port = int(self._server.server_address[1])
        self.requests_by_code: Dict[int, int] = {}
        self._count_lock = threading.Lock()
        from ...obs import metrics as obs_metrics
        self._metrics = (obs_metrics.serving_instruments()
                         if obs_metrics.enabled() else None)
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name=f"lgbt-serve-frontend:{self.port}")
        self._thread.start()
        log.event("serve_frontend", state="started", host=host,
                  port=self.port, qos=dict(self.admission.qos),
                  shed=self.admission.shed_enabled)

    # -- request path ------------------------------------------------------
    def _count(self, code: int) -> None:
        with self._count_lock:
            self.requests_by_code[code] = \
                self.requests_by_code.get(code, 0) + 1
        if self._metrics is not None:
            self._metrics.http_requests.labels(code=str(code)).inc()

    def score(self, model: str, headers, body: bytes
              ) -> Tuple[int, Optional[Dict[str, Any]],
                         Optional[bytes], Optional[Dict[str, str]]]:
        """One scoring request, already read off the wire. Returns
        (status, json_doc, raw_body, extra_headers) — exactly one of
        json_doc/raw_body is non-None. Raises _BadRequest for anything
        malformed, BEFORE the admission/coalescer layers see it."""
        if not model:
            raise _BadRequest("no model name in /v1/score/<model>")
        ctype = (headers.get("Content-Type") or "application/json")
        ctype = ctype.split(";", 1)[0].strip().lower()
        if ctype == "application/octet-stream":
            X = _parse_binary_rows(body, headers)
        else:
            X = _parse_json_rows(body)
        entry = self.service.registry.get(model)
        if entry is None:
            return 404, {"error": f"model {model!r} not resident",
                         "models": self.service.registry.names()}, \
                None, None
        if X.shape[1] != entry.num_features:
            raise _BadRequest(
                f"model {model!r} scores {entry.num_features} features "
                f"per row, got {X.shape[1]}")
        deadline_ms = None
        raw_dl = headers.get("X-Deadline-Ms")
        if raw_dl is not None:
            try:
                deadline_ms = float(raw_dl)
            except ValueError:
                raise _BadRequest(
                    f"X-Deadline-Ms is not a number: {raw_dl!r}") \
                    from None
            if deadline_ms <= 0:
                raise _BadRequest("X-Deadline-Ms must be positive")
        try:
            fut = self.admission.submit(model, X, deadline_ms=deadline_ms)
        except ShedError as exc:
            return 429, {"error": "shed", "model": model,
                         "qos": exc.qos,
                         "burn_rate": round(exc.burn_rate, 4)}, \
                None, {"Retry-After": "1"}
        except RuntimeError as exc:    # admission closed: shutting down
            return 503, {"error": str(exc)}, None, None
        wait_s = (deadline_ms / 1e3 + 5.0 if deadline_ms
                  else _DEFAULT_WAIT_S)
        try:
            margins = fut.result(timeout=wait_s)
        except DeadlineExpired as exc:
            return 504, {"error": "deadline expired", "model": model,
                         "deadline_ms": exc.deadline_ms,
                         "waited_ms": round(exc.waited_ms, 3)}, \
                None, None
        except KeyError as exc:        # evicted between check and flush
            return 404, {"error": str(exc)}, None, None
        except Exception as exc:  # noqa: BLE001 — engine/coalescer error
            return 500, {"error": f"{type(exc).__name__}: {exc}"}, \
                None, None
        margins = np.asarray(margins)
        accept = (headers.get("Accept") or "").lower()
        if "application/octet-stream" in accept:
            shape = ",".join(str(d) for d in margins.shape)
            return 200, None, \
                np.ascontiguousarray(margins, "<f4").tobytes(), \
                {"X-Shape": shape}
        return 200, {"model": model, "rows": int(X.shape[0]),
                     "predictions": margins.tolist()}, None, None

    # -- views -------------------------------------------------------------
    def render_healthz(self) -> Dict[str, Any]:
        svc = self.service
        doc: Dict[str, Any] = {
            "schema": 1,
            "status": "ok",
            "models": svc.registry.names(),
            "qos": {m: QOS_NAMES[p]
                    for m, p in sorted(self.admission.qos.items())},
            "shedding": sorted(self.admission.shedding()),
            "admission": self.admission.stats(),
            "devices": 1,
            "replicas": {},
        }
        if svc.placer is not None:
            pstats = svc.placer.stats()
            doc["devices"] = pstats["devices"]
            doc["replicas"] = {n: len(reps) for n, reps
                               in pstats["models"].items()}
            doc["placement"] = pstats
        return doc

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def close(self) -> None:
        try:
            self._server.shutdown()
            self._server.server_close()
        except Exception:
            pass
        self._thread.join(timeout=5)
        with self._count_lock:
            totals = dict(self.requests_by_code)
        log.event("serve_frontend", state="stopped", port=self.port,
                  requests_by_code={str(k): v
                                    for k, v in sorted(totals.items())})

    def __enter__(self) -> "ScoringFrontend":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
