"""Network front door for the serving plane (ROADMAP item 2).

Three layers between the wire and the coalescer:

- `http.ScoringFrontend` — the socket: ``POST /v1/score/<model>``
  (JSON or packed-binary rows, ``X-Deadline-Ms`` deadlines) and
  ``GET /healthz``, on the exporter's stdlib ThreadingHTTPServer
  pattern;
- `qos.AdmissionController` — per-model QoS classes
  (``tpu_serve_qos``), strict-priority dispatch under a bounded
  in-flight window, burn-rate load shedding with hysteresis (fast 429,
  gold never shed), deadline expiry without dispatch;
- `placement.Placer` — multi-device residency: HBM-headroom
  assignment, request-rate-ranked hot-model replication, shallowest-
  queue routing, per-device LRU budget (``tpu_serve_devices`` /
  ``tpu_serve_replicas``).

`ServingService` wires all three from the ``tpu_serve_*`` params; the
pieces also compose individually (the tests drive each in isolation).
"""
from .http import ScoringFrontend  # noqa: F401
from .placement import Placer, Replica, resolve_devices  # noqa: F401
from .qos import (AdmissionController, DeadlineExpired,  # noqa: F401
                  QOS_CLASSES, QOS_NAMES, ShedError, parse_qos,
                  qos_class)

__all__ = ["ScoringFrontend", "AdmissionController", "Placer", "Replica",
           "ShedError", "DeadlineExpired", "parse_qos", "qos_class",
           "resolve_devices", "QOS_CLASSES", "QOS_NAMES"]
