"""QoS admission control: priority queues + load shedding in front of
the coalescer.

The coalescer (serving/coalescer.py) is FIFO per model — every caller
is equal. A network front door is not: a checkout-scoring model and a
batch-backfill job share the same host, and under saturation the
cheap traffic must not crowd out the important traffic. This module is
that policy layer:

* `parse_qos` maps `tpu_serve_qos="model:class,..."` to per-model
  priority classes — gold (0, highest), silver (1), bronze (2).
  A `default:` item classes unlisted models; otherwise they are bronze.
* `AdmissionController.submit` enqueues into per-class priority queues;
  a dispatcher thread forwards whole requests (never split — the
  coalescer's contract is preserved) in strict class order while the
  in-flight row window (`tpu_serve_admit_rows`) has room. Under
  saturation gold dispatches first, always.
* per-request deadlines (`X-Deadline-Ms`): a request still queued when
  its budget expires is answered with `DeadlineExpired` WITHOUT an
  engine dispatch — scoring it anyway would waste a bucket on an
  answer nobody is waiting for.
* load shedding: when a model's rolling SLO burn rate
  (`RequestTracer.burn_rates`, obs/reqtrace.py) rises to
  `tpu_serve_shed_high`, requests below gold for that model are
  rejected instantly with `ShedError` (the front door maps it to a
  fast 429) until the rate falls back to `tpu_serve_shed_low` —
  hysteresis, so a rate hovering at the watermark doesn't flap.
  Gold is NEVER shed: shedding exists to protect it.

Zero new threads per request: one dispatcher thread per controller,
futures end to end.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Tuple

from ...utils import locks, log

__all__ = ["QOS_CLASSES", "QOS_NAMES", "parse_qos", "qos_class",
           "ShedError", "DeadlineExpired", "AdmissionController"]

# class name -> priority (0 dispatches first and is never shed)
QOS_CLASSES: Dict[str, int] = {"gold": 0, "silver": 1, "bronze": 2}
QOS_NAMES: Tuple[str, ...] = ("gold", "silver", "bronze")
_DEFAULT_CLASS = QOS_CLASSES["bronze"]

# how often (seconds) the shed state re-reads the tracer's burn rates;
# between refreshes admission decisions use the cached state, so the
# per-request cost of shedding is one dict lookup
_SHED_REFRESH_S = 0.05


class ShedError(RuntimeError):
    """Request rejected by load shedding (front door answers 429)."""

    def __init__(self, model: str, qos: str, burn_rate: float) -> None:
        super().__init__(
            f"model {model!r} is shedding {qos} traffic "
            f"(burn_rate={burn_rate:.3f})")
        self.model = model
        self.qos = qos
        self.burn_rate = burn_rate


class DeadlineExpired(TimeoutError):
    """Request deadline elapsed before dispatch (front door: 504)."""

    def __init__(self, model: str, deadline_ms: float,
                 waited_ms: float) -> None:
        super().__init__(
            f"request for {model!r} expired its {deadline_ms:g}ms "
            f"deadline after {waited_ms:.1f}ms in the admission queue")
        self.model = model
        self.deadline_ms = deadline_ms
        self.waited_ms = waited_ms


def parse_qos(spec: str) -> Dict[str, int]:
    """``"ctr:gold,backfill:bronze,default:silver"`` -> name->priority.
    Classes are names or their numeric priorities (0/1/2); the
    ``default`` key classes models not listed. Raises ValueError on a
    malformed item — config validation calls this at startup so a typo
    fails fast, not on the first live request."""
    out: Dict[str, int] = {}
    for item in (s.strip() for s in spec.split(",") if s.strip()):
        if ":" not in item:
            raise ValueError(
                f"tpu_serve_qos item {item!r} is not 'model:class'")
        name, cls = (t.strip() for t in item.rsplit(":", 1))
        cls = cls.lower()
        if cls in QOS_CLASSES:
            pri = QOS_CLASSES[cls]
        elif cls.isdigit() and int(cls) < len(QOS_NAMES):
            pri = int(cls)
        else:
            raise ValueError(
                f"tpu_serve_qos class {cls!r} for {name!r} is not one "
                f"of {'/'.join(QOS_NAMES)} or 0..{len(QOS_NAMES) - 1}")
        if not name:
            raise ValueError(f"tpu_serve_qos item {item!r} has no model")
        out[name] = pri
    return out


def qos_class(qos: Dict[str, int], model: str) -> int:
    """A model's priority under the map (the `default` entry, then
    bronze, for unlisted models)."""
    pri = qos.get(model)
    if pri is None:
        pri = qos.get("default", _DEFAULT_CLASS)
    return pri


class _Pending:
    __slots__ = ("model", "X", "rows", "pri", "deadline_s", "t_submit",
                 "future")

    def __init__(self, model: str, X, pri: int,
                 deadline_ms: Optional[float]) -> None:
        self.model = model
        self.X = X
        self.rows = int(X.shape[0])
        self.pri = pri
        self.t_submit = time.perf_counter()
        self.deadline_s = (None if not deadline_ms
                           else self.t_submit + float(deadline_ms) / 1e3)
        self.future: Future = Future()


@locks.guarded
class AdmissionController:
    """Priority queues + shedding between the front door and the
    coalescer. `submit` is the only client entry point; everything it
    returns or raises is a policy decision made BEFORE the coalescer
    sees the request."""

    def __init__(self, coalescer, qos: Optional[Dict[str, int]] = None,
                 tracer=None, window_rows: int = 0,
                 shed: str = "auto", shed_high: float = 0.5,
                 shed_low: float = 0.25) -> None:
        self.coalescer = coalescer
        self.qos = dict(qos or {})
        self._tracer = tracer
        self.window_rows = (int(window_rows) if window_rows > 0
                            else 2 * coalescer.max_batch_rows)
        # shed=auto: shedding is live exactly when its signal is — the
        # tracer computes burn rates only when an SLO is configured
        self.shed_enabled = (shed == "on" or (
            shed == "auto" and tracer is not None
            and getattr(tracer, "slo_ms", 0) > 0))
        self.shed_high = float(shed_high)
        self.shed_low = float(shed_low)
        self._cv = threading.Condition()
        self._queues: List[deque] = [deque()
                                     for _ in QOS_NAMES]  # guarded-by: _cv
        self._inflight_rows = 0                           # guarded-by: _cv
        self._closed = False                              # guarded-by: _cv
        # shed state: model -> burn rate at trip time; refreshed from
        # the tracer at most every _SHED_REFRESH_S
        self._shedding: Dict[str, float] = {}             # guarded-by: _cv
        self._shed_checked = 0.0                          # guarded-by: _cv
        self.requests = 0
        self.dispatched = 0
        self.sheds = 0
        self.sheds_by_class = [0] * len(QOS_NAMES)
        self.deadline_expired = 0
        self._deadline_logged = 0.0                       # guarded-by: _cv
        from ...obs import metrics as obs_metrics
        self._metrics = (obs_metrics.serving_instruments()
                         if obs_metrics.enabled() else None)
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="lgbt-serve-admission")
        self._thread.start()

    # -- client side -------------------------------------------------------
    def submit(self, model: str, X,
               deadline_ms: Optional[float] = None) -> Future:
        """Admit one request. Raises ShedError immediately when the
        model is shedding this request's class; otherwise returns a
        Future that resolves to raw margins, DeadlineExpired, or the
        coalescer's error."""
        pri = qos_class(self.qos, model)
        with self._cv:
            if self._closed:
                raise RuntimeError("admission controller is closed")
            self._refresh_shed_state(time.perf_counter())
            burn = self._shedding.get(model)
            if burn is not None and pri > 0:
                self.sheds += 1
                self.sheds_by_class[pri] += 1
                shed_exc = ShedError(model, QOS_NAMES[pri], burn)
            else:
                shed_exc = None
                self.requests += 1
                req = _Pending(model, X, pri, deadline_ms)
                self._queues[pri].append(req)
                self._cv.notify()
        if shed_exc is not None:
            if self._metrics is not None:
                self._metrics.shed.labels(
                    model=model, qos=QOS_NAMES[pri]).inc()
            raise shed_exc
        if self._metrics is not None:
            self._metrics.admit_depth.labels(
                qos=QOS_NAMES[pri]).set(len(self._queues[pri]))
        return req.future

    def shedding(self) -> Dict[str, float]:
        """Models currently shedding -> burn rate at trip (live view for
        /healthz and tests)."""
        with self._cv:
            self._refresh_shed_state(time.perf_counter())
            return dict(self._shedding)

    def stats(self) -> Dict[str, Any]:
        with self._cv:
            return {
                "requests": self.requests,
                "dispatched": self.dispatched,
                "sheds": self.sheds,
                "sheds_by_class": {QOS_NAMES[i]: n
                                   for i, n in
                                   enumerate(self.sheds_by_class) if n},
                "deadline_expired": self.deadline_expired,
                "queued": {QOS_NAMES[i]: len(q)
                           for i, q in enumerate(self._queues) if q},
                "inflight_rows": self._inflight_rows,
                "window_rows": self.window_rows,
                "shed_enabled": self.shed_enabled,
                "shedding": dict(self._shedding),
            }

    def close(self) -> None:
        """Stop the dispatcher; queued requests fail fast."""
        with self._cv:
            if self._closed:
                return
            self._closed = True
            for q in self._queues:
                for req in q:
                    req.future.set_exception(
                        RuntimeError("admission controller closed"))
                q.clear()
            self._cv.notify()
        self._thread.join(timeout=30)

    # -- shed hysteresis ---------------------------------------------------
    def _refresh_shed_state(self, now: float) -> None:  # guarded-by: caller
        """Re-read burn rates and flip per-model shed state with
        hysteresis; rate-limited so admission stays O(1) per request."""
        if not self.shed_enabled or self._tracer is None:
            return
        if now - self._shed_checked < _SHED_REFRESH_S:
            return
        self._shed_checked = now
        try:
            rates = self._tracer.burn_rates()
        except Exception:   # tracer mid-close must not kill admission
            return
        for model, rate in rates.items():
            tripped = model in self._shedding
            if not tripped and rate >= self.shed_high:
                self._shedding[model] = float(rate)
                log.event("serve_shed", model=model, state="on",
                          burn_rate=round(float(rate), 4),
                          high=self.shed_high, low=self.shed_low,
                          sheds=self.sheds)
            elif tripped and rate <= self.shed_low:
                del self._shedding[model]
                log.event("serve_shed", model=model, state="off",
                          burn_rate=round(float(rate), 4),
                          high=self.shed_high, low=self.shed_low,
                          sheds=self.sheds)

    # -- dispatcher thread -------------------------------------------------
    def _pop(self, now: float):  # guarded-by: caller
        """Next dispatchable request, strict class order; expired
        requests anywhere in the queues are answered (without dispatch)
        on the way. None when every queue is empty."""
        for pri, q in enumerate(self._queues):
            while q:
                req = q.popleft()
                if req.deadline_s is not None and now > req.deadline_s:
                    self._expire(req, now)
                    continue
                if self._metrics is not None:
                    self._metrics.admit_depth.labels(
                        qos=QOS_NAMES[pri]).set(len(q))
                return req
        return None

    def _expire_overdue(self, now: float) -> None:  # guarded-by: caller
        """Expire deadline-passed requests while the window is
        saturated — a full window must not pin a doomed request in the
        queue past its budget (`_pop` only runs when there is room)."""
        for q in self._queues:
            overdue = [r for r in q if r.deadline_s is not None
                       and now > r.deadline_s]
            for req in overdue:
                q.remove(req)
                self._expire(req, now)

    def _expire(self, req: _Pending, now: float) -> None:  # guarded-by: caller
        self.deadline_expired += 1
        waited_ms = (now - req.t_submit) * 1e3
        deadline_ms = (req.deadline_s - req.t_submit) * 1e3
        if now - self._deadline_logged > 1.0:   # rate-limited event
            self._deadline_logged = now
            log.event("serve_deadline", model=req.model,
                      deadline_ms=round(deadline_ms, 3),
                      waited_ms=round(waited_ms, 3),
                      expired_total=self.deadline_expired)
        if self._metrics is not None:
            self._metrics.deadline_expired.labels(model=req.model).inc()
        req.future.set_exception(
            DeadlineExpired(req.model, deadline_ms, waited_ms))

    def _loop(self) -> None:
        while True:
            with self._cv:
                now = time.perf_counter()
                req = None
                if self._inflight_rows < self.window_rows:
                    req = self._pop(now)
                else:
                    self._expire_overdue(now)
                if req is None:
                    if self._closed:
                        return
                    # bounded wait so queued deadlines expire on time
                    # even when the window is saturated or traffic stops
                    self._cv.wait(timeout=0.01)
                    continue
                self._inflight_rows += req.rows
            try:
                inner = self.coalescer.submit(req.model, req.X)
            except BaseException as exc:  # noqa: BLE001 — via the future
                with self._cv:
                    self._inflight_rows -= req.rows
                    self._cv.notify()
                req.future.set_exception(exc)
                continue
            with self._cv:
                self.dispatched += 1
            inner.add_done_callback(
                lambda f, r=req: self._finish(r, f))

    def _finish(self, req: _Pending, inner: Future) -> None:
        """Coalescer resolved: release the window, mirror the outcome."""
        with self._cv:
            self._inflight_rows -= req.rows
            self._cv.notify()
        exc = inner.exception()
        if exc is not None:
            req.future.set_exception(exc)
        else:
            req.future.set_result(inner.result())
