"""Train-to-serve pipeline: watch a checkpoint dir, hot-swap on change.

`resilience.CheckpointManager` publishes checkpoints atomically behind a
MANIFEST.json pointer (tmp + os.replace). The watcher polls that pointer
— and ONLY that pointer; it never globs `ckpt_*`, because directory
listings see the trainer's staging tmp dirs and retention's deletions,
exactly the torn state the manifest hides. Every failure mode of a
concurrent writer (manifest mid-rewrite, checkpoint dir swept between
the pointer read and the model read) reads as "no new version yet" and
is retried on the next tick.

On a new `latest`, the replacement forest is built and warmed on-device
FIRST (`ModelRegistry.swap` compiles the new engine's programs for the
buckets live traffic uses before installing it), then the registry entry
flips atomically. In-flight requests keep the old engine alive by
refcount; no request fails or blocks on a compile. Exactly one swap
happens per distinct manifest version, however many poll ticks observe
it — the ledger's `serve_swap` note count is the CI contract.
"""
from __future__ import annotations

import threading
from typing import Optional

from ..utils import log
from .registry import ModelRegistry, load_checkpoint_model_text

__all__ = ["CheckpointWatcher"]


class CheckpointWatcher:
    """Polls one checkpoint directory and keeps one registry entry
    current. `start()` spawns the daemon poll thread; `poll_once()` is
    the synchronous step (tests and the service's load path drive it
    directly)."""

    def __init__(self, registry: ModelRegistry, name: str, directory: str,
                 interval_s: float = 0.5, tracer=None) -> None:
        self.registry = registry
        self.name = name
        self.directory = directory
        self.interval_s = max(float(interval_s), 0.01)
        self._tracer = tracer
        self.polls = 0
        self.swapped: list = []          # versions installed, in order
        self._last_version: Optional[str] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- core step ---------------------------------------------------------
    def poll_once(self) -> bool:
        """One poll: install the manifest's latest version if it is new.
        Returns True when a load/swap happened. Never raises on a
        concurrently-written directory — unreadable states are retried
        next tick."""
        self.polls += 1
        got = load_checkpoint_model_text(self.directory)
        if got is None:
            return False
        model_str, version = got
        if version == self._last_version:
            return False
        try:
            if self.registry.get(self.name) is None:
                entry = self.registry.load(self.name, model_str=model_str,
                                           version=version)
                entry.source = self.directory
            else:
                self.registry.swap(self.name, model_str, version=version,
                                   source=self.directory)
        except ValueError as exc:
            # torn/garbage model text from a non-atomic writer: skip this
            # version and retry the pointer next tick
            log.event("serve_watch_bad_model", model=self.name,
                      version=version, error=str(exc))
            if self._tracer is not None:
                self._tracer.note("serve_watch_bad_model",
                                  model=self.name, version=version,
                                  error=str(exc))
            return False
        self._last_version = version
        self.swapped.append(version)
        return True

    # -- thread ------------------------------------------------------------
    def start(self) -> "CheckpointWatcher":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"lgbt-serve-watch-{self.name}")
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception as exc:  # noqa: BLE001 — watcher must survive
                log.event("serve_watch_error", model=self.name,
                          error=f"{type(exc).__name__}: {exc}")
            self._stop.wait(self.interval_s)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None
