"""Online serving service on top of `serve.ForestEngine`.

The engine (serve/engine.py) is a single-model library: a device-
resident stacked forest with pow2 shape buckets. This package is the
service around it — what ROADMAP item 3 calls the production traffic
layer:

- `ModelRegistry` (registry.py): many named boosters resident at once,
  HBM-budget LRU eviction with real byte accounting, loads from model
  text or straight from a `resilience/` checkpoint manifest.
- `RequestCoalescer` (coalescer.py): concurrent predict requests
  coalesce into full shape buckets under a latency SLO
  (`tpu_serve_max_batch_wait_ms` / `tpu_serve_max_batch_rows`).
- `CheckpointWatcher` (watcher.py): zero-downtime hot-swap — polls the
  checkpoint MANIFEST pointer, warms the replacement forest on-device,
  atomically swaps the registry entry; in-flight requests finish on the
  old forest.
- `ServingService` (service.py): the facade the CLI `task=serve` and
  `tools/bench_serve_traffic.py` drive.
- `MetricsExporter` (exporter.py): the `/metrics` + `/metrics.json`
  HTTP endpoint over the process metrics registry (obs/metrics.py) and
  HBM accountant (obs/memory.py); wired by `tpu_serve_metrics_port`.
- `frontend/` (ScoringFrontend / AdmissionController / Placer): the
  network front door — `POST /v1/score/<model>` over QoS priority
  admission with burn-rate load shedding, and multi-device model
  placement with hot-model replication; wired by `tpu_serve_port`,
  `tpu_serve_qos` and `tpu_serve_devices`.
"""
from .coalescer import RequestCoalescer  # noqa: F401
from .exporter import MetricsExporter  # noqa: F401
from .frontend import (AdmissionController, DeadlineExpired,  # noqa: F401
                       Placer, ScoringFrontend, ShedError)
from .registry import ModelEntry, ModelRegistry  # noqa: F401
from .service import ServingService  # noqa: F401
from .watcher import CheckpointWatcher  # noqa: F401

__all__ = ["ModelEntry", "ModelRegistry", "RequestCoalescer",
           "CheckpointWatcher", "ServingService", "MetricsExporter",
           "ScoringFrontend", "AdmissionController", "Placer",
           "ShedError", "DeadlineExpired"]
