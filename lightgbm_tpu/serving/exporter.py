"""MetricsExporter: the serving host's scrape endpoint.

A stdlib `ThreadingHTTPServer` (no new dependencies) bound to
127.0.0.1 serving two views of the same process-wide registry
(`obs/metrics.py`):

* ``GET /metrics``       — Prometheus text exposition format v0.0.4,
  including per-model latency histograms with interpolated _p50/_p99
  series and the HBM accountant gauges;
* ``GET /metrics.json``  — the versioned snapshot dict (registry +
  memory reconciliation) for tooling that prefers JSON, plus a
  ``serving`` block with per-model AOT artifact state and compact-plan
  bytes saved when a model registry is attached;
* ``GET /debug/requests`` — the request tracer's live view (recent
  ring, slowest-request table, burn rates) when ``tpu_serve_trace`` is
  on; ``{"enabled": false}`` otherwise;
* ``GET /debug/timeline`` — the unified run timeline (Chrome-trace
  ``trace_events`` JSON, ``obs/timeline.py``) built live from the
  attached trace directory; ``{"enabled": false}`` when the process
  runs without a file-backed trace dir. Save the body to a file and
  open it in Perfetto / ``chrome://tracing``.

Every scrape refreshes the HBM accountant first (`obs.memory.snapshot`
reads owner callbacks + backend memory_stats at that moment), so the
gauges are live, not last-event stale. Scrapes run on the HTTP server's
threads and never touch the request path.

Wired by `ServingService` when ``tpu_serve_metrics_port`` is nonzero;
``port=0`` here binds an OS-assigned ephemeral port (the CLI param's 0
means "off" — tests use 0 to avoid port races and read ``.port``).
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

from ..obs import memory as obs_memory
from ..obs import metrics as obs_metrics

__all__ = ["MetricsExporter"]

PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class _Handler(BaseHTTPRequestHandler):
    exporter: "MetricsExporter" = None  # set per server instance

    def do_GET(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler API
        path = self.path.split("?", 1)[0]
        try:
            if path == "/metrics":
                body = self.exporter.render_prometheus().encode()
                ctype = PROM_CONTENT_TYPE
            elif path == "/metrics.json":
                body = json.dumps(self.exporter.render_json(),
                                  sort_keys=True, default=str).encode()
                ctype = "application/json"
            elif path == "/debug/requests":
                body = json.dumps(self.exporter.render_requests(),
                                  sort_keys=True, default=str).encode()
                ctype = "application/json"
            elif path == "/debug/timeline":
                body = json.dumps(self.exporter.render_timeline(),
                                  sort_keys=True, default=str).encode()
                ctype = "application/json"
            elif path in ("/", "/healthz"):
                body = b"ok\n"
                ctype = "text/plain"
            else:
                self.send_error(404)
                return
        except Exception as exc:  # a broken callback must not kill scrapes
            self.send_error(500, str(exc)[:100])
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):  # silence per-request stderr noise
        pass


class MetricsExporter:
    """HTTP scrape endpoint over the process metrics registry."""

    def __init__(self, port: int, host: str = "127.0.0.1",
                 tracer=None, registry=None,
                 trace_dir: Optional[str] = None) -> None:
        obs_metrics.enable()
        self.tracer = tracer
        # model registry (serving/registry.py): when attached,
        # /metrics.json carries per-model AOT + compaction detail
        self.registry = registry
        # trace dir (obs/trace.py file-backed sink): when attached,
        # /debug/timeline merges its streams live on every GET
        self.trace_dir = trace_dir
        handler = type("_BoundHandler", (_Handler,), {"exporter": self})
        self._server = ThreadingHTTPServer((host, int(port)), handler)
        self._server.daemon_threads = True
        self.host = host
        self.port = int(self._server.server_address[1])
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name=f"lgbt-metrics-exporter:{self.port}")
        self._thread.start()

    # -- rendering (also the testing seam — no HTTP needed) ---------------
    def render_prometheus(self) -> str:
        obs_memory.snapshot()          # refresh hbm_* gauges first
        return obs_metrics.to_prometheus()

    def render_json(self) -> Dict[str, Any]:
        doc = {"schema": obs_metrics.SCHEMA_VERSION,
               "metrics": obs_metrics.snapshot(),
               "memory": obs_memory.snapshot()}
        if self.registry is not None:
            doc["serving"] = {
                "models": self.registry.aot_compact_stats()}
        return doc

    def render_requests(self) -> Dict[str, Any]:
        """The /debug/requests document (request-trace ring + slow
        table); a cheap {"enabled": false} stub with tracing off."""
        if self.tracer is None:
            return {"schema": 1, "enabled": False}
        return dict({"schema": 1, "enabled": True},
                    **self.tracer.snapshot())

    def render_timeline(self) -> Dict[str, Any]:
        """The /debug/timeline document: the merged Chrome-trace JSON
        built from the attached trace dir at scrape time, so the lanes
        grow as the run does; {"enabled": false} with no trace dir."""
        if not self.trace_dir:
            return {"schema": 1, "enabled": False}
        from ..obs import timeline as obs_timeline
        return obs_timeline.build_timeline(self.trace_dir)

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def close(self) -> None:
        try:
            self._server.shutdown()
            self._server.server_close()
        except Exception:
            pass
        self._thread.join(timeout=5)

    def __enter__(self) -> "MetricsExporter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
