"""ServingService: registry + coalescer + watchers behind one facade.

The piece the CLI's `task=serve` and the traffic bench drive. Configured
through the same params surface as training (`tpu_serve_*` in
config.py), so a serving host is launched with the familiar
`key=value` vocabulary:

    svc = ServingService(params={"tpu_serve_hbm_budget_mb": 512,
                                 "tpu_serve_max_batch_wait_ms": 2})
    svc.load_model("ctr", model_file="ctr.txt")
    svc.watch("ranker", "/ckpts/ranker")       # hot-swaps on new manifests
    margins = svc.predict("ctr", X)            # coalesced under the SLO

`predict` returns RAW margins (the ForestEngine output) — objective
transforms stay a client concern, matching the engine's own contract.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

from ..config import Config
from .coalescer import RequestCoalescer
from .registry import ModelEntry, ModelRegistry
from .watcher import CheckpointWatcher

__all__ = ["ServingService"]


class ServingService:
    """One serving host: many resident models, one request queue."""

    def __init__(self, params: Optional[Dict[str, Any]] = None,
                 ledger=None) -> None:
        cfg = Config.from_params(params or {})
        self.config = cfg
        if cfg.tpu_debug_locks:
            # install the checking __setattr__ BEFORE the registry/
            # coalescer are constructed (their first guarded writes
            # happen in __init__ and stay exempt either way)
            from ..utils import locks
            locks.set_debug_locks(True)
        # metrics must be on BEFORE the registry/coalescer resolve their
        # instrument handles (they bind once at construction); the
        # tracer binds its SLO instruments the same way, so it is built
        # here too — after enable, before the registry
        self.exporter = None
        if cfg.tpu_serve_metrics_port or cfg.tpu_metrics:
            from ..obs import metrics as obs_metrics
            obs_metrics.enable()
        self.tracer = None
        if cfg.tpu_serve_trace:
            from ..obs.reqtrace import RequestTracer
            self.tracer = RequestTracer(
                slo_ms=cfg.tpu_serve_slo_ms,
                sample=cfg.tpu_serve_trace_sample,
                ring_size=cfg.tpu_serve_trace_ring,
                out_dir=cfg.tpu_serve_trace_dir)
        # multi-device placer (frontend/placement.py): with more than
        # one device the HBM budget becomes PER-DEVICE and the placer's
        # per-device LRU replaces the registry's global sweep — both
        # enforcing at once would fight over the same bytes
        self.placer = None
        place_on = cfg.tpu_serve_devices != 1
        if place_on:
            from .frontend.placement import Placer, resolve_devices
            place_devices = resolve_devices(cfg.tpu_serve_devices)
            place_on = len(place_devices) > 1
        self.registry = ModelRegistry(
            hbm_budget_mb=(0.0 if place_on
                           else cfg.tpu_serve_hbm_budget_mb),
            warm_rows=cfg.tpu_serve_warm_rows,
            ledger=ledger, tracer=self.tracer,
            compact=cfg.tpu_serve_compact,
            compact_tol=cfg.tpu_serve_compact_tol,
            aot_dir=cfg.tpu_serve_aot_dir)
        if place_on:
            self.placer = Placer(self.registry, devices=place_devices,
                                 budget_mb=cfg.tpu_serve_hbm_budget_mb,
                                 max_replicas=cfg.tpu_serve_replicas,
                                 warm_rows=cfg.tpu_serve_warm_rows,
                                 tracer=self.tracer)
        self.coalescer = RequestCoalescer(
            self.registry,
            max_batch_wait_ms=cfg.tpu_serve_max_batch_wait_ms,
            max_batch_rows=cfg.tpu_serve_max_batch_rows,
            tracer=self.tracer, placer=self.placer)
        # QoS admission + network front door (frontend/): built when a
        # front-door port or a QoS map asks for them; in-process
        # predict()/predict_async() stay direct-to-coalescer
        self.admission = None
        self.frontend = None
        if cfg.tpu_serve_port or cfg.tpu_serve_qos:
            from .frontend.qos import AdmissionController, parse_qos
            self.admission = AdmissionController(
                self.coalescer,
                qos=parse_qos(cfg.tpu_serve_qos),
                tracer=self.tracer,
                window_rows=cfg.tpu_serve_admit_rows,
                shed=cfg.tpu_serve_shed,
                shed_high=cfg.tpu_serve_shed_high,
                shed_low=cfg.tpu_serve_shed_low)
        if cfg.tpu_serve_port:
            from .frontend.http import ScoringFrontend
            self.frontend = ScoringFrontend(self,
                                            port=cfg.tpu_serve_port)
        if cfg.tpu_serve_metrics_port:
            from .exporter import MetricsExporter
            # /debug/timeline merges whatever file-backed trace streams
            # this process has: the live obs.trace dir when training ran
            # here, else the request tracer's out_dir
            from ..obs import trace as obs_trace
            tdir = obs_trace.trace_dir() if obs_trace.enabled() else None
            tdir = tdir or cfg.tpu_serve_trace_dir or None
            self.exporter = MetricsExporter(cfg.tpu_serve_metrics_port,
                                            tracer=self.tracer,
                                            registry=self.registry,
                                            trace_dir=tdir)
        self._watchers: Dict[str, CheckpointWatcher] = {}
        self._closed = False

    # -- model management --------------------------------------------------
    def load_model(self, name: str, model_str: Optional[str] = None,
                   model_file: Optional[str] = None,
                   checkpoint_dir: Optional[str] = None) -> ModelEntry:
        entry = self.registry.load(name, model_str=model_str,
                                   model_file=model_file,
                                   checkpoint_dir=checkpoint_dir)
        if self.placer is not None:
            # watcher swaps skip this path; route() re-places lazily on
            # the first post-swap batch (engine identity check)
            self.placer.place(name, entry)
        return entry

    def watch(self, name: str, checkpoint_dir: str) -> CheckpointWatcher:
        """Serve `name` from a checkpoint directory and keep it current:
        the initial version loads synchronously when one is readable,
        then a poll thread hot-swaps on every new manifest version."""
        w = self._watchers.get(name)
        if w is not None:
            return w
        w = CheckpointWatcher(self.registry, name, checkpoint_dir,
                              interval_s=self.config.tpu_serve_watch_interval_s,
                              tracer=self.tracer)
        w.poll_once()
        self._watchers[name] = w
        return w.start()

    # -- scoring -----------------------------------------------------------
    def predict_async(self, name: str, X):
        """Enqueue; returns a concurrent.futures.Future of raw margins."""
        return self.coalescer.submit(name, X)

    def predict(self, name: str, X, timeout: Optional[float] = None):
        return self.coalescer.submit(name, X).result(timeout=timeout)

    # -- lifecycle ---------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        out = {
            "registry": self.registry.stats(),
            "coalescer": self.coalescer.stats(),
            "watchers": {n: {"polls": w.polls,
                             "versions": list(w.swapped)}
                         for n, w in self._watchers.items()},
        }
        if self.tracer is not None:
            out["reqtrace"] = self.tracer.totals()
        if self.exporter is not None:
            out["metrics_endpoint"] = self.exporter.url
        if self.admission is not None:
            out["admission"] = self.admission.stats()
        if self.placer is not None:
            out["placement"] = self.placer.stats()
        if self.frontend is not None:
            out["frontend"] = {
                "url": self.frontend.url,
                "requests_by_code": {
                    str(k): v for k, v in
                    sorted(self.frontend.requests_by_code.items())}}
        return out

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        # stop accepting from the wire first, then drain inward:
        # frontend -> admission -> watchers -> coalescer
        if self.frontend is not None:
            self.frontend.close()
        if self.admission is not None:
            self.admission.close()
        for w in self._watchers.values():
            w.stop()
        # coalescer drains before the tracer closes, so every in-flight
        # request still lands its trace row (started == finished)
        self.coalescer.close()
        if self.exporter is not None:
            self.exporter.close()
        if self.tracer is not None:
            self.tracer.close()

    def __enter__(self) -> "ServingService":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
