"""Distributed runtime: mesh construction, learner selection, resume
rescatter — the glue that makes ``tree_learner=data|feature|voting`` a
first-class `engine.train` / ``task=train`` path instead of a
hand-constructed object.

Topology resolution (``num_shards``):

1. ``tpu_dist_devices > 0`` pins the mesh to the first N visible devices
   (the operator's explicit slice carve-out);
2. else ``num_machines > 1`` — the reference's own topology knob — asks
   for that many shards;
3. else every visible device joins the mesh.

Either way the request is clamped to the devices that exist, so a config
written for a v5p-16 also runs under 8 emulated CPU devices, just
narrower. All three params are runtime-only (model_text/checkpoint
RUNTIME_ONLY_PARAMS), matching the reference: with ``tpu_use_f64_hist``
the data-parallel model is bitwise-independent of topology, so the dump
must be too.
"""
from __future__ import annotations

from typing import Optional

__all__ = ["active", "build_mesh", "make_learner", "num_shards",
           "rescatter_scores", "stream_shard_mesh"]

_PARALLEL_MODES = ("data", "feature", "voting")


def num_shards(cfg) -> int:
    """Mesh width the config asks for, clamped to visible devices."""
    import jax
    nd = len(jax.devices())
    if int(getattr(cfg, "tpu_dist_devices", 0)) > 0:
        return max(1, min(int(cfg.tpu_dist_devices), nd))
    if int(cfg.num_machines) > 1:
        return max(1, min(int(cfg.num_machines), nd))
    return nd


def active(cfg) -> bool:
    """True when a parallel tree_learner should actually go SPMD (a
    1-wide mesh degenerates to the serial device learner)."""
    return cfg.tree_learner in _PARALLEL_MODES and num_shards(cfg) > 1


def build_mesh(cfg, axis_name: str = "data"):
    """1-D mesh over the first `num_shards(cfg)` devices."""
    from ..parallel import default_mesh
    return default_mesh(num_shards(cfg), axis_name)


def stream_shard_mesh(cfg):
    """Mesh for stream-to-shard ingest, or None when the streamed load
    should assemble the host matrix (the legacy two-step path).

    Sharding the stream only pays when the training run will consume
    the row shards in place: ``tree_learner=data|voting`` (feature-
    parallel replicates rows). ``tpu_stream_shard="auto"`` additionally
    requires the mesh the dist runtime would build to be wider than one
    device; ``"on"`` shards even a 1-wide mesh (the serial device
    learner re-gathers the host matrix on demand); ``"off"`` never
    shards."""
    mode = str(getattr(cfg, "tpu_stream_shard", "auto")).lower()
    if mode == "off":
        return None
    if cfg.tree_learner not in ("data", "voting"):
        return None
    if mode != "on" and not active(cfg):
        return None
    return build_mesh(cfg, "data")


def make_learner(cfg, train_data):
    """Factory entry for GBDT: build the mesh, shard the dataset onto it
    (data/voting — feature-parallel replicates rows), construct the
    learner, announce the topology on the event channel."""
    from ..parallel import make_parallel_learner
    from ..utils import log

    axis = "feature" if cfg.tree_learner == "feature" else "data"
    mesh = build_mesh(cfg, axis)
    if cfg.tree_learner in ("data", "voting"):
        train_data.shard(mesh, axis)      # cache-primed; learner reuses
    learner = make_parallel_learner(cfg, train_data, mesh=mesh)
    kinds = sorted({d.platform for d in mesh.devices.flat})
    log.event("dist_init", tree_learner=cfg.tree_learner,
              shards=int(mesh.devices.size), axis=axis,
              device_kinds=",".join(kinds))
    return learner


def rescatter_scores(gbdt) -> bool:
    """After a checkpoint restore placed the gathered ``[K, N]`` score
    buffers as single-device arrays, push them back onto the learner's
    mesh (rows sharded along the data axis) so the resumed round loop
    runs SPMD without an implicit broadcast-and-reshard on its first
    dispatch. Values are untouched — bitwise resume parity is carried by
    the array contents, placement is performance. Returns True when a
    rescatter happened."""
    learner = getattr(gbdt, "learner", None)
    mesh = getattr(learner, "mesh", None)
    axis = getattr(learner, "axis_name", None)
    if mesh is None or axis is None or axis != "data":
        return False
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from ..utils import log

    nd = int(mesh.devices.size)
    moved = 0

    def _place(arr):
        nonlocal moved
        n = int(arr.shape[-1])
        spec = P(None, axis) if n % nd == 0 else P()
        moved += 1
        return jax.device_put(arr, NamedSharding(mesh, spec))

    gbdt.train_score.score = _place(gbdt.train_score.score)
    for su in gbdt.valid_scores:
        # valid rows never leave their host-side metric path sharded;
        # replicate them so eval programs see a mesh-committed buffer
        su.score = jax.device_put(su.score, NamedSharding(mesh, P()))
        moved += 1
    log.event("dist_resume", shards=nd, buffers=moved)
    return True
