"""Distributed bin-boundary finding.

Re-creates the reference's global bin-sync protocol
(`DataParallelTreeLearner` setup + `DatasetLoader::CostructFromSampleData`
with `Network::GlobalSyncUpByMin/Max` and the sampled-quantile allgather,
`src/io/dataset_loader.cpp:535`, `src/network/network.cpp`): every worker
samples ITS contiguous row block, the per-shard sample contributions are
merged in block order, and the merged sample — bitwise-identical to what a
single host would have drawn — feeds the exact same `BinMapper.find_bin`
on every shard.

The parity argument, which `tests/test_dist.py` asserts bitwise:

- the sample INDEX set is drawn from one shared seed
  (`cfg.data_random_seed`, the reference broadcasts its random seeds the
  same way) and sorted, so every shard agrees on it without traffic;
- shard ``s`` owns global rows ``[s*per, (s+1)*per)`` — the contiguous
  block layout of `DataParallelTreeLearner` — and contributes exactly the
  sampled rows inside its block;
- concatenating the contributions in shard order re-creates the sorted
  global sample verbatim, so the merged boundaries equal the single-host
  boundaries bin for bin (no tolerance involved);
- the mapper "broadcast" is emulated by a `to_dict`/`from_dict`
  round-trip — the same wire format the binary dataset file uses — so a
  serialization-lossy field would fail parity here, not on a real mesh.

On a real multi-host mesh the concatenate becomes an allgather of
variable-length per-shard slices; the merge order and everything after it
are unchanged, which is the point: the sync protocol is host-side numpy
either way, and the devices only ever see the finished bins.
"""
from __future__ import annotations

import math
import time
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..io.binning import BIN_CATEGORICAL, BIN_NUMERICAL, BinMapper

__all__ = [
    "find_bin_mappers_distributed",
    "merged_sample",
    "sample_indices",
    "shard_sample_indices",
]


def sample_indices(n: int, sample_cnt: int, seed: int) -> np.ndarray:
    """The canonical sorted bin-construction sample draw — byte-for-byte
    the draw `Dataset.from_matrix` makes. Every sampling consumer
    (single-host, distributed shards, the streaming ingest's bounded
    sample pass) goes through THIS function so their boundaries are
    bitwise-equal by construction, not by tolerance."""
    rng = np.random.RandomState(seed)
    if sample_cnt < n:
        return np.sort(rng.choice(n, sample_cnt, replace=False))
    return np.arange(n, dtype=np.int64)


def shard_sample_indices(n: int, sample_cnt: int, seed: int,
                         num_shards: int) -> List[np.ndarray]:
    """Per-shard GLOBAL sample indices: the single shared draw split by
    contiguous row block. ``concatenate(result)`` is exactly the sorted
    single-host sample index array."""
    idx = sample_indices(n, sample_cnt, seed)
    per = int(math.ceil(n / num_shards))
    return [idx[(idx >= s * per) & (idx < (s + 1) * per)]
            for s in range(num_shards)]


def merged_sample(data: np.ndarray, sample_cnt: int, seed: int,
                  num_shards: int) -> np.ndarray:
    """The global sample matrix as the distributed protocol produces it:
    per-shard contributions concatenated in block order."""
    parts = shard_sample_indices(len(data), sample_cnt, seed, num_shards)
    return np.concatenate([np.asarray(data[p]) for p in parts], axis=0)


def find_bin_mappers_distributed(
        data: np.ndarray, cfg, cat_set: Set[int],
        num_shards: int) -> Tuple[List[BinMapper], Dict[str, float]]:
    """Global-sync bin finding over `num_shards` contiguous row blocks.

    Returns ``(mappers, stats)`` where `stats` carries the host wall time
    of the whole sync (`bin_sync_ms`, the calibration term of the same
    name in obs/terms.py) and the per-shard sample counts.
    """
    t0 = time.perf_counter()
    n, f = data.shape
    sample_cnt = min(n, max(cfg.bin_construct_sample_cnt, 1))
    parts = shard_sample_indices(n, sample_cnt, cfg.data_random_seed,
                                 num_shards)
    # "allgather": block-ordered concatenation of each shard's sampled rows
    sample = np.concatenate([np.asarray(data[p]) for p in parts], axis=0)
    mappers: List[BinMapper] = []
    for j in range(f):
        col = np.asarray(sample[:, j], dtype=np.float64)
        nonzero = col[~((col >= -1e-35) & (col <= 1e-35))]
        m = BinMapper()
        bt = BIN_CATEGORICAL if j in cat_set else BIN_NUMERICAL
        m.find_bin(nonzero, total_sample_cnt=len(col),
                   max_bin=cfg.max_bin,
                   min_data_in_bin=cfg.min_data_in_bin,
                   min_split_data=cfg.min_data_in_leaf,
                   bin_type=bt, use_missing=cfg.use_missing,
                   zero_as_missing=cfg.zero_as_missing)
        # broadcast emulation: the mapper every shard actually uses has
        # been through the wire format once
        mappers.append(BinMapper.from_dict(m.to_dict()))
    stats = {
        "bin_sync_ms": (time.perf_counter() - t0) * 1e3,
        "shards": num_shards,
        "sample_cnt_per_shard": [int(len(p)) for p in parts],
    }
    return mappers, stats
