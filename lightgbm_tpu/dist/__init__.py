"""Distributed training runtime.

The subsystem that promotes the `parallel/` tree learners into the
first-class `engine.train` / CLI path: mesh construction and learner
selection (`runtime.py`), distributed bin-boundary finding mirroring the
reference's ``GlobalSyncUpByMin/Max`` + sample sync (`binning.py`), and
sharded-score checkpoint rescatter. The reference implements this plane
in `src/network/` (Allreduce/ReduceScatter/Allgather over MPI sockets);
here every collective is an XLA op inside one jitted SPMD program,
lowered to ICI all-reduces on real hardware.

This module also owns the one `shard_map` compatibility seam: newer jax
exposes `jax.shard_map(..., check_vma=)`, older releases only
`jax.experimental.shard_map.shard_map(..., check_rep=)`. Every
shard_map in the tree routes through `dist.shard_map` so the learners
run on both.
"""
from __future__ import annotations

import jax

__all__ = ["shard_map", "runtime", "binning"]


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = False):
    """`jax.shard_map` across jax versions (check_vma == check_rep)."""
    fn = getattr(jax, "shard_map", None)
    if fn is not None:
        return fn(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_vma)
