"""Resilient training runtime: atomic full-state checkpoints, bitwise
resume, preemption handling, and a deterministic fault-injection harness.

Four parts (see docs/Resilience.md):

- ``checkpoint``: full-training-state checkpoints — model text, the
  bagging/GOSS/DART + feature-sampling RNG streams, the f32 score
  arrays, the iteration counter and early-stopping state — written
  atomically (payload directory staged under a tmp name, ``os.replace``
  renamed, then a MANIFEST.json pointer tmp+renamed) on a rolling
  retention window.
- ``resume``: restore that continues training bitwise-identically to
  the uninterrupted run, by reinstalling the captured RNG streams and
  score arrays rather than replaying them.
- ``preempt``: SIGTERM/SIGINT handling scoped to the round loop — the
  in-flight round finishes, checkpoint + ledger flush, and the CLI
  exits with EXIT_PREEMPTED (75, EX_TEMPFAIL).
- ``faults`` + ``retry``: param/env-driven deterministic fault
  injection (kill at round R, transient error at the N-th device
  dispatch) and bounded retry-with-backoff around dispatch sites, with
  every fault/retry/recovery recorded as ledger notes and log events.
"""
from .checkpoint import (CheckpointManager, atomic_write_text,
                         prune_snapshots, training_signature)
from .faults import FaultPlan, InjectedTransientError
from .preempt import EXIT_PREEMPTED, PreemptGuard
from .resume import load_latest, restore
from .retry import call_with_retry, is_transient

__all__ = [
    "CheckpointManager", "atomic_write_text", "prune_snapshots",
    "training_signature", "FaultPlan", "InjectedTransientError",
    "EXIT_PREEMPTED", "PreemptGuard", "load_latest", "restore",
    "call_with_retry", "is_transient",
]
