"""Preemption handling scoped to the round loop.

TPU pools reclaim preemptible slices with SIGTERM; interactive runs get
SIGINT. Either way the right response mid-training is the same: FINISH
the in-flight round (its device work is already dispatched; abandoning
it wastes the round and can leave donated buffers dangling), flush one
final checkpoint + the ledger, and exit with a code schedulers can
distinguish from a crash.

``PreemptGuard`` is installed by ``engine.train`` just before the round
loop when checkpointing is active, and uninstalled right after. The
signal handler only sets a flag — it never raises into the middle of a
device dispatch — and the loop checks the flag once per round at its
existing post-round seam, so the pipelined paths keep their single
round fence. A second SIGINT while the guard is draining restores the
default behavior (an impatient operator can still kill the process).

Exit code: 75 (BSD ``EX_TEMPFAIL`` — "temporary failure, retry later"),
returned by the CLI so wrapper scripts can re-submit the same command,
which auto-resumes from the manifest.
"""
from __future__ import annotations

import os
import signal
import threading
from typing import Dict, Optional

from ..utils import log

EXIT_PREEMPTED = 75  # EX_TEMPFAIL: rerun the same command to resume


class PreemptGuard:
    """Flag-setting SIGTERM/SIGINT handler with install/uninstall."""

    SIGNALS = (signal.SIGTERM, signal.SIGINT)

    def __init__(self) -> None:
        self.triggered = False
        self.signal_name: Optional[str] = None
        self._old: Dict[int, object] = {}
        self._installed = False

    def install(self) -> "PreemptGuard":
        """Install handlers; inert (never triggers) when not on the
        main thread — Python only allows signal handlers there."""
        if threading.current_thread() is not threading.main_thread():
            return self
        try:
            for sig in self.SIGNALS:
                self._old[sig] = signal.signal(sig, self._handle)
            self._installed = True
        except (ValueError, OSError):
            self._old.clear()
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        for sig, old in self._old.items():
            try:
                signal.signal(sig, old)
            except (ValueError, OSError):
                pass
        self._old.clear()
        self._installed = False

    def _handle(self, signum, frame) -> None:
        name = signal.Signals(signum).name
        if self.triggered and signum == signal.SIGINT:
            # second Ctrl-C: stop draining, restore default, re-raise
            self.uninstall()
            raise KeyboardInterrupt
        first = not self.triggered
        self.triggered = True
        self.signal_name = name
        if first:
            log.warning(f"{name} received: finishing the in-flight round, "
                        "then flushing checkpoint + ledger "
                        f"(exit code {EXIT_PREEMPTED})")
            log.event("preempt", signal=name, pid=os.getpid())

    # context-manager sugar for tests
    def __enter__(self) -> "PreemptGuard":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()
