"""Deterministic fault injection: prove the resilience machinery works
by killing training at an exact round or failing an exact device
dispatch — from a param (``tpu_fault_spec``) or environment variable
(``LGBT_FAULTS``), so tests and CI drive it without code changes.

Spec grammar (comma-separated, all indices deterministic):

- ``kill@R``       SIGTERM to own pid before round R runs — the
                   PreemptGuard machinery (finish round, checkpoint,
                   exit 75) is exercised end to end, not simulated.
- ``int@R``        same with SIGINT.
- ``transient@N``  raise :class:`InjectedTransientError` at the N-th
                   device dispatch (1-based, counted across the whole
                   run) — exercises retry.py's backoff loop. The error
                   raises BEFORE the real dispatch runs, so donated
                   buffers are untouched and the retry is exact.

Every injected fault is recorded as a ledger ``note`` and an
``[Event]`` log record, so a run's fault history is auditable from its
telemetry alone.
"""
from __future__ import annotations

import os
import signal
from typing import Optional, Set

from ..utils import log


class InjectedTransientError(RuntimeError):
    """A deliberately-injected retriable device-dispatch failure."""


class FaultPlan:
    """Parsed fault spec + the mutable counters that make each fault
    fire exactly once. One plan per GBDT instance (the dispatch counter
    must be shared by every dispatch site)."""

    def __init__(self, spec: str, telemetry=None) -> None:
        self.spec = spec
        self.telemetry = telemetry
        self.kill_round: Optional[int] = None
        self.kill_signal = signal.SIGTERM
        self.transient_at: Set[int] = set()
        self.dispatch_n = 0
        self._killed = False
        for tok in spec.split(","):
            tok = tok.strip().lower()
            if not tok:
                continue
            if "@" not in tok:
                raise ValueError(f"bad fault token {tok!r} in {spec!r} "
                                 "(want kind@index)")
            kind, _, idx = tok.partition("@")
            if not idx.lstrip("-").isdigit():
                raise ValueError(f"bad fault index in {tok!r}")
            at = int(idx)
            if kind == "kill":
                self.kill_round = at
            elif kind == "int":
                self.kill_round = at
                self.kill_signal = signal.SIGINT
            elif kind == "transient":
                self.transient_at.add(at)
            else:
                raise ValueError(f"unknown fault kind {kind!r} in {spec!r}")

    @classmethod
    def from_config(cls, cfg, telemetry=None) -> Optional["FaultPlan"]:
        spec = cfg.tpu_fault_spec or os.environ.get("LGBT_FAULTS", "")
        if not spec:
            return None
        return cls(spec, telemetry=telemetry)

    # ------------------------------------------------------------------
    def note(self, what: str, **fields) -> None:
        log.event("fault", fault=what, **fields)
        if self.telemetry is not None:
            self.telemetry.commit({"kind": "note", "note": what, **fields})

    def on_round(self, round_idx: int) -> None:
        """Engine pre-round hook: deliver the scheduled kill signal to
        our own pid. With a PreemptGuard installed this drains
        gracefully; without one the process dies — honest kill
        semantics either way."""
        if self._killed or self.kill_round is None \
                or round_idx != self.kill_round:
            return
        self._killed = True
        # "fault_kind": both log.event's first arg and the ledger record
        # discriminator are already named "kind"
        self.note("fault_injected", fault_kind="kill", round=round_idx,
                  signal=signal.Signals(self.kill_signal).name)
        os.kill(os.getpid(), self.kill_signal)

    def next_dispatch(self) -> int:
        """Count a LOGICAL device dispatch (retries of the same dispatch
        keep its number)."""
        self.dispatch_n += 1
        return self.dispatch_n

    def should_fail(self, dispatch_n: int) -> bool:
        return dispatch_n in self.transient_at

    def raise_transient(self, dispatch_n: int, what: str) -> None:
        self.note("fault_injected", fault_kind="transient",
                  dispatch=dispatch_n, site=what)
        raise InjectedTransientError(
            f"injected transient fault at device dispatch {dispatch_n} "
            f"({what})")
