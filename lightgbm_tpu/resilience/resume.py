"""Bitwise-identical resume from a checkpoint.py checkpoint.

The restore path REINSTALLS captured state instead of replaying it:

- trees come from the checkpoint's model text (decimal repr round-trips
  the stored float64/float32 values exactly, so a re-serialized resumed
  model is byte-identical to the uninterrupted run's);
- the f32 train/valid score arrays come from arrays.npz — replaying the
  loaded trees would accumulate in a different order AND through the
  text repr, breaking bitwise continuation;
- the bagging/GOSS/DART and feature-sampling RNG streams are reinstated
  by full Mersenne state (never re-seeded: a re-seeded ``_bag_rng``
  restarts at round 0's draws and silently diverges);
- early-stopping callback state (best score/iter per metric) goes back
  into the callback closures via their ``set_ckpt_state`` hooks.

``engine.train`` calls ``load_latest`` + ``restore`` automatically when
``tpu_checkpoint_dir`` holds a valid manifest whose training signature
matches the current config; a signature or dataset-shape mismatch is
WARNED and training starts fresh (the stale checkpoints age out through
retention).
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

import numpy as np

from ..utils import log
from .checkpoint import (MANIFEST_NAME, SCHEMA_VERSION, install_rng_states,
                         read_manifest)


def load_latest(mgr) -> Optional[Dict[str, Any]]:
    """Validate the manifest + latest checkpoint under `mgr.directory`
    and return a restore bundle {dir, state, model_text, arrays}, or
    None when there is nothing (valid) to resume from."""
    man = read_manifest(mgr.directory)
    if man is None:
        return None
    if man.get("schema", 0) > SCHEMA_VERSION:
        log.warning(f"checkpoint manifest schema {man.get('schema')} is "
                    f"newer than this build ({SCHEMA_VERSION}); ignoring "
                    f"{os.path.join(mgr.directory, MANIFEST_NAME)}")
        return None
    cdir = os.path.join(mgr.directory, str(man["latest"]))
    paths = {n: os.path.join(cdir, n)
             for n in ("model.txt", "state.json", "arrays.npz")}
    if not all(os.path.isfile(p) for p in paths.values()):
        log.warning(f"checkpoint {cdir} is incomplete; ignoring it")
        return None
    try:
        with open(paths["state.json"]) as fh:
            state = json.load(fh)
    except (OSError, ValueError) as exc:
        log.warning(f"unreadable checkpoint state at {cdir}: {exc}")
        return None
    if mgr.signature and state.get("signature") != mgr.signature:
        log.warning(
            f"checkpoint at {cdir} was written under a different training "
            f"config (signature {state.get('signature')!r} != "
            f"{mgr.signature!r}); starting fresh")
        return None
    with open(paths["model.txt"]) as fh:
        model_text = fh.read()
    arrays = dict(np.load(paths["arrays.npz"]))
    return {"dir": cdir, "state": state, "model_text": model_text,
            "arrays": arrays}


def restore(booster, bundle: Dict[str, Any], callbacks=()) -> int:
    """Reinstall `bundle` into a freshly-constructed training booster
    (AFTER its valid sets were attached — their score arrays are
    overwritten here). Returns the loop iteration to continue from."""
    from ..models.model_text import load_model_from_string
    gbdt = booster._gbdt
    state = bundle["state"]
    arrays = bundle["arrays"]

    if int(state["num_data"]) != int(gbdt.num_data) \
            or int(state["num_class"]) != int(gbdt.num_tree_per_iteration):
        log.warning(
            f"checkpoint at {bundle['dir']} does not match this dataset "
            f"(rows {state['num_data']} vs {gbdt.num_data}, classes "
            f"{state['num_class']} vs {gbdt.num_tree_per_iteration}); "
            "starting fresh")
        return 0

    import jax.numpy as jnp
    trees = load_model_from_string(bundle["model_text"])["trees"]
    gbdt.models = list(trees)
    gbdt.iter = int(state["iter"])
    gbdt.shrinkage_rate = float(state["shrinkage_rate"])

    ts = arrays["train_score"]
    if tuple(ts.shape) != tuple(gbdt.train_score.score.shape):
        log.warning(f"checkpoint score shape {ts.shape} does not match "
                    f"{tuple(gbdt.train_score.score.shape)}; starting fresh")
        gbdt.models = []
        gbdt.iter = 0
        return 0
    gbdt.train_score.score = jnp.asarray(ts)
    for i, su in enumerate(gbdt.valid_scores):
        key = f"valid_score_{i}"
        if key not in arrays:
            log.warning(f"checkpoint lacks {key} (valid sets changed); "
                        "its scores will rebuild from the loaded trees")
            continue
        su.score = jnp.asarray(arrays[key])
    # distributed runs: push the gathered score buffers back onto the
    # learner's mesh so the resumed loop is SPMD from its first dispatch
    # (values untouched — bitwise parity rides the contents)
    from ..dist.runtime import rescatter_scores
    rescatter_scores(gbdt)

    bag_idx = arrays.get("bag_data_indices")
    if bag_idx is not None and bag_idx.size:
        gbdt.bag_data_indices = np.asarray(bag_idx, np.int32)
    else:
        gbdt.bag_data_indices = None
    gbdt.bag_data_cnt = int(state["bag_data_cnt"])

    install_rng_states(gbdt, state["rng"])

    pend = arrays.get("pending_numsplits")
    gbdt._pending_numsplits = (
        [jnp.asarray(int(v), jnp.int32) for v in pend]
        if pend is not None and pend.size else [])

    dart = state.get("dart")
    if dart is not None and hasattr(gbdt, "tree_weight"):
        gbdt.tree_weight = [float(w) for w in dart["tree_weight"]]
        gbdt.sum_weight = float(dart["sum_weight"])

    cb_states = state.get("callbacks") or {}
    for cb in callbacks:
        key = getattr(cb, "ckpt_key", None)
        setter = getattr(cb, "set_ckpt_state", None)
        if key and setter is not None and key in cb_states:
            setter(cb_states[key])

    booster.best_iteration = int(state.get("best_iteration", -1))

    start_iter = int(state["loop_iter"])
    log.info(f"resuming training from checkpoint {bundle['dir']} "
             f"(iteration {start_iter})")
    log.event("resume", iter=gbdt.iter, loop_iter=start_iter,
              checkpoint=bundle["dir"], reason=state.get("reason"))
    led = gbdt.telemetry
    if led is not None:
        led.commit({"kind": "note", "note": "resume",
                    "iter": gbdt.iter, "loop_iter": start_iter,
                    "checkpoint": bundle["dir"],
                    "ledger_round_offset": state.get("ledger_rounds", 0)})
    return start_iter
