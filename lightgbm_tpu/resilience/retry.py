"""Bounded retry-with-backoff around device dispatch sites.

Scope is deliberately narrow: only errors that look TRANSIENT are
retried — the injected :class:`~.faults.InjectedTransientError`, and
XLA runtime errors whose status text names a retriable condition
(UNAVAILABLE / ABORTED / DEADLINE_EXCEEDED / preemption). Everything
else propagates on the first raise: retrying a shape error or OOM loop
only hides bugs.

Caveat for real (non-injected) failures: a dispatch that donated its
input buffers may leave them invalidated when it raises, in which case
the retry fails fast with the resulting buffer error — best-effort by
design. Injected faults raise BEFORE the real dispatch (faults.py), so
the deterministic test path is always exact.

Every retry and the eventual recovery/give-up is recorded as a ledger
note and an ``[Event]`` record.
"""
from __future__ import annotations

import time
from typing import Any, Callable, Optional, Tuple

from ..utils import log
from .faults import FaultPlan, InjectedTransientError

# substrings of XlaRuntimeError/RuntimeError text that mark a device
# error worth retrying (TPU preemption/donation races surface this way)
TRANSIENT_MARKERS = ("UNAVAILABLE", "ABORTED", "DEADLINE_EXCEEDED",
                     "preempted", "preemption")


def is_transient(exc: BaseException) -> bool:
    if isinstance(exc, InjectedTransientError):
        return True
    if type(exc).__name__ == "XlaRuntimeError":
        text = str(exc)
        return any(m in text for m in TRANSIENT_MARKERS)
    return False


def call_with_retry(fn: Callable, args: Tuple[Any, ...], *, what: str,
                    plan: Optional[FaultPlan], max_retries: int,
                    backoff_s: float, telemetry=None) -> Any:
    """Run ``fn(*args)`` with fault injection + bounded exponential
    backoff. `what` names the dispatch site in telemetry."""
    n = plan.next_dispatch() if plan is not None else 0
    attempt = 0
    while True:
        try:
            if plan is not None and attempt == 0 and plan.should_fail(n):
                plan.raise_transient(n, what)
            out = fn(*args)
            if attempt > 0:
                from ..obs import metrics as obs_metrics
                obs_metrics.note_retry_event("recovered")
                log.event("retry_recovered", what=what, dispatch=n,
                          attempts=attempt)
                if telemetry is not None:
                    telemetry.commit({"kind": "note",
                                      "note": "retry_recovered",
                                      "what": what, "dispatch": n,
                                      "attempts": attempt})
            return out
        except Exception as exc:
            from ..obs import metrics as obs_metrics
            if not is_transient(exc) or attempt >= max_retries:
                if attempt > 0:
                    obs_metrics.note_retry_event("exhausted")
                    log.event("retry_exhausted", what=what, dispatch=n,
                              attempts=attempt, error=str(exc)[:200])
                raise
            delay = backoff_s * (2.0 ** attempt)
            attempt += 1
            obs_metrics.note_retry_event("retry")
            log.warning(f"transient error in {what} (dispatch {n}), "
                        f"retry {attempt}/{max_retries} in {delay:.3f}s: "
                        f"{exc}")
            log.event("retry", what=what, dispatch=n, attempt=attempt,
                      delay_s=round(delay, 4), error=str(exc)[:200])
            if telemetry is not None:
                telemetry.commit({"kind": "note", "note": "retry",
                                  "what": what, "dispatch": n,
                                  "attempt": attempt,
                                  "delay_s": round(delay, 4)})
            if delay > 0:
                time.sleep(delay)
