"""Atomic full-training-state checkpoints (reference treats snapshots as
a first-class training feature, `gbdt.cpp:289-293`; this module extends
them from model-text-only to the COMPLETE training state so a resumed
run continues bitwise-identically — see resume.py).

Checkpoint layout (one directory per checkpoint under
``tpu_checkpoint_dir``)::

    <dir>/MANIFEST.json          atomic pointer: latest + retained list
    <dir>/ckpt_000010/model.txt  model text at the checkpoint iteration
    <dir>/ckpt_000010/state.json iter, RNG streams, early-stop state,
                                 training signature, ledger offset
    <dir>/ckpt_000010/arrays.npz f32 train/valid score arrays, bagging
                                 indices, pending numsplit flags

Atomicity: the payload directory is staged under a tmp name in the same
filesystem and ``os.replace``-renamed into place; MANIFEST.json is then
rewritten tmp+rename. A reader either sees the previous manifest or the
new one — never a half-written checkpoint. Retention keeps the newest
``tpu_snapshot_keep`` checkpoints.

Why score arrays and not tree replay: model text stores leaf values
through a decimal repr, and re-applying trees uses a different f32
accumulation order than training — both would break bitwise resume.
The checkpointed f32 arrays restore the exact training-time bits.
"""
from __future__ import annotations

import hashlib
import json
import os
import shutil
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..utils import log

SCHEMA_VERSION = 1
MANIFEST_NAME = "MANIFEST.json"
_CKPT_PREFIX = "ckpt_"

# params that describe the run's infrastructure, not the training math:
# excluded from the checkpoint-compatibility signature so a resumed run
# may e.g. drop the fault spec or change retention without the manifest
# being rejected
RUNTIME_ONLY_PARAMS = frozenset({
    "tpu_checkpoint_dir", "tpu_checkpoint_freq", "tpu_snapshot_keep",
    "tpu_fault_spec", "tpu_retry_max", "tpu_retry_backoff_s",
    "tpu_trace", "tpu_trace_dir", "tpu_compile_cache_dir",
    "snapshot_freq", "output_model", "input_model", "output_result",
    "num_threads", "verbosity",
    "tpu_serve_hbm_budget_mb", "tpu_serve_max_batch_wait_ms",
    "tpu_serve_max_batch_rows", "tpu_serve_watch_interval_s",
    "tpu_serve_warm_rows", "tpu_metrics", "tpu_serve_metrics_port",
    "tpu_serve_hold_s", "tpu_serve_trace", "tpu_serve_trace_dir",
    "tpu_serve_trace_sample", "tpu_serve_trace_ring", "tpu_serve_slo_ms",
    "tpu_serve_aot_dir", "tpu_serve_compact", "tpu_serve_compact_tol",
    # network front door (serving/frontend/): admission, shedding and
    # placement shape traffic, never the trained trees
    "tpu_serve_port", "tpu_serve_qos", "tpu_serve_shed",
    "tpu_serve_shed_high", "tpu_serve_shed_low", "tpu_serve_admit_rows",
    "tpu_serve_devices", "tpu_serve_replicas",
    "tpu_profile", "tpu_profile_every",
    "tpu_profile_capture", "tpu_debug_locks",
    # timeline + straggler/anomaly watches (obs/timeline.py,
    # obs/straggler.py): observability of the run, not training math
    "tpu_timeline", "tpu_straggler_threshold", "tpu_straggler_rounds",
    "tpu_anomaly_factor", "tpu_anomaly_window",
    # sweep-trainer infrastructure (sweep/): a fleet checkpoint may be
    # resumed with different sweep plumbing, and a sequential checkpoint
    # is mode-independent anyway
    "tpu_sweep_mode", "tpu_sweep_checkpoint_dir",
    "tpu_sweep_checkpoint_freq", "tpu_sweep_hbm_budget_mb",
    "tpu_sweep_max_fleet",
    # topology: trees are bit-identical across tree_learner / shard-count
    # choices (distributed parity contract), so a checkpoint taken on one
    # topology may resume on another — e.g. a preempted 4-chip run
    # finishing on a single chip
    "tree_learner", "num_machines", "is_parallel", "is_parallel_find_bin",
    "tpu_dist_devices",
    # how the matrix was ingested does not change what it binned to
    "tpu_stream_chunk_rows", "tpu_stream_shard",
    "tpu_stream_pipeline_depth",
})


def training_signature(cfg) -> str:
    """sha1 over every Config field that affects training math (the
    compile-cache signature minus RUNTIME_ONLY_PARAMS). Two runs with
    the same signature produce the same trees, so a checkpoint from one
    may seed the other."""
    from ..compile_cache import config_signature
    items = [(k, v) for k, v in config_signature(cfg)
             if k not in RUNTIME_ONLY_PARAMS]
    blob = json.dumps(items, sort_keys=True, default=str)
    return hashlib.sha1(blob.encode()).hexdigest()[:16]


def atomic_write_text(path: str, text: str) -> None:
    """tmp + rename in the destination directory (same filesystem, so
    the rename is atomic); a reader never sees a torn file."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = os.path.join(d, f".tmp.{os.getpid()}.{os.path.basename(path)}")
    with open(tmp, "w") as fh:
        fh.write(text)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


def prune_snapshots(output_model: str, keep: int) -> List[str]:
    """Rolling retention for the CLI's ``<output_model>.snapshot_iter_K``
    files: keep the newest `keep` by iteration number, delete the rest.
    Returns the removed paths."""
    import glob
    if keep <= 0:
        return []
    snaps = []
    for p in glob.glob(f"{output_model}.snapshot_iter_*"):
        tail = p.rsplit("snapshot_iter_", 1)[-1]
        if tail.isdigit():
            snaps.append((int(tail), p))
    snaps.sort()
    removed = []
    excess = snaps[:-keep] if len(snaps) > keep else []
    for _, p in excess:
        try:
            os.remove(p)
            removed.append(p)
        except OSError:
            pass
    return removed


def _encode_rng(rs: np.random.RandomState) -> Dict[str, Any]:
    name, keys, pos, has_gauss, cached = rs.get_state()
    return {"name": str(name), "keys": [int(k) for k in keys],
            "pos": int(pos), "has_gauss": int(has_gauss),
            "cached_gaussian": float(cached)}


def _install_rng(rs: np.random.RandomState, enc: Dict[str, Any]) -> None:
    rs.set_state((enc["name"], np.asarray(enc["keys"], np.uint32),
                  int(enc["pos"]), int(enc["has_gauss"]),
                  float(enc["cached_gaussian"])))


def capture_rng_states(gbdt) -> Dict[str, Any]:
    """Every host RNG stream training consumes: the bagging/GOSS stream
    (gbdt._bag_rng), the DART drop stream, and the learner's column-
    sampling stream. Captured by full Mersenne state, not by seed —
    resume REINSTALLS the stream instead of replaying it."""
    out: Dict[str, Any] = {"bag": _encode_rng(gbdt._bag_rng)}
    feat = getattr(gbdt.learner, "_feat_rng", None)
    if feat is not None:
        out["feat"] = _encode_rng(feat)
    drop = getattr(gbdt, "_drop_rng", None)
    if drop is not None:
        out["drop"] = _encode_rng(drop)
    return out


def install_rng_states(gbdt, enc: Dict[str, Any]) -> None:
    _install_rng(gbdt._bag_rng, enc["bag"])
    if "feat" in enc and getattr(gbdt.learner, "_feat_rng", None) is not None:
        _install_rng(gbdt.learner._feat_rng, enc["feat"])
    if "drop" in enc and getattr(gbdt, "_drop_rng", None) is not None:
        _install_rng(gbdt._drop_rng, enc["drop"])


def read_manifest(directory: str) -> Optional[Dict[str, Any]]:
    """The manifest dict, or None when absent/corrupt (a torn write
    cannot happen — see atomic_write_text — but a partial scp can)."""
    path = os.path.join(directory, MANIFEST_NAME)
    if not os.path.isfile(path):
        return None
    try:
        with open(path) as fh:
            man = json.load(fh)
    except (OSError, ValueError):
        return None
    if not isinstance(man, dict) or "latest" not in man:
        return None
    return man


class CheckpointManager:
    """Owns one checkpoint directory: periodic + preemption writes,
    manifest maintenance, rolling retention, and write-cost accounting
    (surfaced by bench.py's resume stage)."""

    def __init__(self, directory: str, keep: int = 3, freq: int = 10,
                 signature: str = "") -> None:
        self.directory = directory
        self.keep = max(1, int(keep))
        self.freq = max(1, int(freq))
        self.signature = signature
        self.writes = 0
        self.write_s = 0.0

    @classmethod
    def from_config(cls, cfg) -> "CheckpointManager":
        freq = cfg.tpu_checkpoint_freq
        if freq <= 0:
            freq = cfg.snapshot_freq if cfg.snapshot_freq > 0 else 10
        return cls(cfg.tpu_checkpoint_dir, keep=cfg.tpu_snapshot_keep,
                   freq=freq, signature=training_signature(cfg))

    def due(self, completed_rounds: int) -> bool:
        return completed_rounds % self.freq == 0

    # ------------------------------------------------------------------
    def write(self, booster, loop_iter: int, callbacks=(),
              reason: str = "periodic") -> str:
        """Capture and atomically persist the FULL training state after
        `loop_iter` completed rounds. Returns the checkpoint path."""
        t0 = time.perf_counter()
        gbdt = booster._gbdt
        # one consistency point: resolve speculative/pipelined device
        # work so models/scores/RNG agree (reuses the round-loop seam —
        # no tracing fence is issued here)
        gbdt._sync_train_score()
        gbdt.materialized_models()

        state: Dict[str, Any] = {
            "schema": SCHEMA_VERSION,
            "iter": int(gbdt.iter),
            "loop_iter": int(loop_iter),
            "signature": self.signature,
            "reason": reason,
            "time": time.time(),
            "num_data": int(gbdt.num_data),
            "num_class": int(gbdt.num_tree_per_iteration),
            "bag_data_cnt": int(gbdt.bag_data_cnt),
            "shrinkage_rate": float(gbdt.shrinkage_rate),
            "best_iteration": int(getattr(booster, "best_iteration", -1)),
            "rng": capture_rng_states(gbdt),
        }
        # DART bookkeeping (tree weights ride the drop/normalize math)
        if hasattr(gbdt, "tree_weight"):
            state["dart"] = {
                "tree_weight": [float(w) for w in gbdt.tree_weight],
                "sum_weight": float(gbdt.sum_weight),
            }
        cb_states: Dict[str, Any] = {}
        for cb in callbacks:
            get = getattr(cb, "get_ckpt_state", None)
            key = getattr(cb, "ckpt_key", None)
            if get is not None and key:
                cb_states[key] = get()
        state["callbacks"] = cb_states
        led = gbdt.telemetry
        if led is not None:
            state["ledger_rounds"] = len(led.round_records())
            state["ledger_path"] = led.path

        arrays: Dict[str, np.ndarray] = {
            "train_score": np.asarray(gbdt.train_score.score, np.float32),
        }
        for i, su in enumerate(gbdt.valid_scores):
            arrays[f"valid_score_{i}"] = np.asarray(su.score, np.float32)
        arrays["bag_data_indices"] = (
            np.asarray(gbdt.bag_data_indices, np.int32)
            if gbdt.bag_data_indices is not None
            else np.zeros(0, np.int32))
        if gbdt._pending_numsplits:
            import jax
            arrays["pending_numsplits"] = np.asarray(
                jax.device_get(gbdt._pending_numsplits), np.int32).ravel()
        else:
            arrays["pending_numsplits"] = np.zeros(0, np.int32)

        name = f"{_CKPT_PREFIX}{int(gbdt.iter):06d}"
        final = os.path.join(self.directory, name)
        tmp = os.path.join(self.directory, f".tmp.{os.getpid()}.{name}")
        os.makedirs(self.directory, exist_ok=True)
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp)
        with open(os.path.join(tmp, "model.txt"), "w") as fh:
            fh.write(booster.model_to_string())
        with open(os.path.join(tmp, "state.json"), "w") as fh:
            json.dump(state, fh, sort_keys=True)
        np.savez(os.path.join(tmp, "arrays.npz"), **arrays)
        shutil.rmtree(final, ignore_errors=True)
        os.replace(tmp, final)

        self._update_manifest(name, state)
        dt = time.perf_counter() - t0
        self.writes += 1
        self.write_s += dt
        log.event("checkpoint", iter=state["iter"], path=final,
                  reason=reason, write_s=round(dt, 4))
        if led is not None:
            led.commit({"kind": "note", "note": "checkpoint",
                        "iter": state["iter"], "reason": reason,
                        "write_s": round(dt, 4)})
        return final

    def _update_manifest(self, name: str, state: Dict[str, Any]) -> None:
        man = read_manifest(self.directory) or {
            "schema": SCHEMA_VERSION, "checkpoints": []}
        kept = [c for c in man.get("checkpoints", []) if c != name]
        kept.append(name)
        # retention: newest `keep` by iteration number
        kept.sort(key=lambda c: int(c[len(_CKPT_PREFIX):]))
        drop, kept = kept[:-self.keep], kept[-self.keep:]
        man.update({
            "schema": SCHEMA_VERSION,
            "latest": name,
            "iter": state["iter"],
            "loop_iter": state["loop_iter"],
            "signature": self.signature,
            "checkpoints": kept,
        })
        atomic_write_text(os.path.join(self.directory, MANIFEST_NAME),
                          json.dumps(man, sort_keys=True, indent=1))
        for c in drop:
            shutil.rmtree(os.path.join(self.directory, c),
                          ignore_errors=True)
