"""Training callbacks (reference `python-package/lightgbm/callback.py`):
print_evaluation, record_evaluation, reset_parameter, early_stopping with the
EarlyStopException control flow the engine relies on (`callback.py:78-236`).
"""
from __future__ import annotations

import collections
from typing import Callable, Dict, List

CallbackEnv = collections.namedtuple(
    "CallbackEnv",
    ["model", "params", "iteration", "begin_iteration", "end_iteration",
     "evaluation_result_list", "telemetry"])
# `telemetry` (an obs.ledger.RoundLedger, or None when tpu_trace is off)
# defaults so third-party construction of the older 6-field env keeps
# working
CallbackEnv.__new__.__defaults__ = (None,)


class EarlyStopException(Exception):
    def __init__(self, best_iteration: int, best_score) -> None:
        super().__init__()
        self.best_iteration = best_iteration
        self.best_score = best_score


def _format_eval_result(value, show_stdv: bool = True) -> str:
    if len(value) == 4:
        return f"{value[0]}'s {value[1]}: {value[2]:g}"
    if len(value) == 5:
        if show_stdv:
            return f"{value[0]}'s {value[1]}: {value[2]:g} + {value[4]:g}"
        return f"{value[0]}'s {value[1]}: {value[2]:g}"
    raise ValueError("Wrong metric value")


def print_evaluation(period: int = 1, show_stdv: bool = True) -> Callable:
    def _callback(env: CallbackEnv) -> None:
        if period > 0 and env.evaluation_result_list \
                and (env.iteration + 1) % period == 0:
            result = "\t".join(
                _format_eval_result(x, show_stdv)
                for x in env.evaluation_result_list)
            print(f"[{env.iteration + 1}]\t{result}")
    _callback.order = 10
    return _callback


def record_evaluation(eval_result: Dict) -> Callable:
    if not isinstance(eval_result, dict):
        raise TypeError("eval_result should be a dictionary")
    eval_result.clear()

    def _init(env: CallbackEnv) -> None:
        for data_name, eval_name, _, _ in env.evaluation_result_list:
            eval_result.setdefault(data_name, collections.OrderedDict())
            eval_result[data_name].setdefault(eval_name, [])

    def _callback(env: CallbackEnv) -> None:
        if not eval_result:
            _init(env)
        for data_name, eval_name, result, _ in env.evaluation_result_list:
            eval_result.setdefault(data_name, collections.OrderedDict())
            eval_result[data_name].setdefault(eval_name, [])
            eval_result[data_name][eval_name].append(result)
    _callback.order = 20
    return _callback


def log_telemetry(period: int = 1) -> Callable:
    """Fold per-round eval metric values into the telemetry ledger and
    re-emit the round record on the structured log channel every
    `period` iterations (0: ledger-fold only, no events). A no-op
    unless training runs with `tpu_trace` — the ledger rides in
    ``env.telemetry`` (or on the booster for externally-built envs)."""
    def _callback(env: CallbackEnv) -> None:
        led = env.telemetry
        if led is None:
            led = getattr(getattr(env.model, "_gbdt", None),
                          "telemetry", None)
        if led is None:
            return
        if env.evaluation_result_list:
            led.record_eval(env.iteration, env.evaluation_result_list)
        if period > 0 and (env.iteration + 1) % period == 0:
            rec = led.last_round()
            if rec is not None:
                from .utils import log
                log.event("telemetry", **{k: v for k, v in rec.items()
                                          if k != "kind"})
    _callback.order = 25
    return _callback


def reset_parameter(**kwargs) -> Callable:
    """Reset parameters on schedule (reference callback.py:128-172);
    values may be lists (per-iteration) or functions iter->value."""
    def _callback(env: CallbackEnv) -> None:
        new_parameters = {}
        for key, value in kwargs.items():
            if isinstance(value, list):
                if len(value) != env.end_iteration - env.begin_iteration:
                    raise ValueError(
                        f"Length of list {key!r} has to equal to "
                        "'num_boost_round'.")
                new_param = value[env.iteration - env.begin_iteration]
            else:
                new_param = value(env.iteration - env.begin_iteration)
            if new_param != env.params.get(key, None):
                new_parameters[key] = new_param
        if new_parameters:
            if env.model is not None:
                env.model.reset_parameter(new_parameters)
            env.params.update(new_parameters)
    _callback.before_iteration = True
    _callback.order = 10
    return _callback


def early_stopping(stopping_rounds: int, first_metric_only: bool = False,
                   verbose: bool = True) -> Callable:
    """reference callback.py:174-236."""
    best_score: List = []
    best_iter: List = []
    best_score_list: List = []
    cmp_op: List = []
    cmp_flags: List = []   # bigger_is_better per metric (checkpointable
    #                        stand-in for the cmp_op lambdas)
    enabled = [True]
    first_metric = [""]

    def _init(env: CallbackEnv) -> None:
        enabled[0] = not any(
            env.params.get(alias, "") == "dart"
            for alias in ("boosting", "boosting_type", "boost"))
        if not enabled[0]:
            if verbose:
                print("Early stopping is not available in dart mode")
            return
        if not env.evaluation_result_list:
            raise ValueError(
                "For early stopping, at least one dataset and eval metric "
                "is required for evaluation")
        if verbose:
            print(f"Training until validation scores don't improve for "
                  f"{stopping_rounds} rounds.")
        first_metric[0] = env.evaluation_result_list[0][1]
        for _, _, _, bigger_better in env.evaluation_result_list:
            best_iter.append(0)
            best_score_list.append(None)
            cmp_flags.append(bool(bigger_better))
            if bigger_better:
                best_score.append(float("-inf"))
                cmp_op.append(lambda x, y: x > y)
            else:
                best_score.append(float("inf"))
                cmp_op.append(lambda x, y: x < y)

    def _final_iteration_check(env, eval_name_splitted, i) -> None:
        if env.iteration == env.end_iteration - 1:
            if verbose:
                print("Did not meet early stopping. Best iteration is:\n"
                      f"[{best_iter[i] + 1}]\t"
                      + "\t".join(_format_eval_result(x)
                                  for x in best_score_list[i]))
            raise EarlyStopException(best_iter[i], best_score_list[i])

    def _callback(env: CallbackEnv) -> None:
        if not cmp_op:
            _init(env)
        if not enabled[0]:
            return
        for i in range(len(env.evaluation_result_list)):
            data_name, eval_name, score, _ = env.evaluation_result_list[i]
            if best_score_list[i] is None or cmp_op[i](score, best_score[i]):
                best_score[i] = score
                best_iter[i] = env.iteration
                best_score_list[i] = env.evaluation_result_list
            if first_metric_only and first_metric[0] != eval_name:
                continue
            if data_name == "cv_agg" or env.model is None \
                    or data_name != env.model.name_train_set:
                if env.iteration - best_iter[i] >= stopping_rounds:
                    if verbose:
                        print("Early stopping, best iteration is:\n"
                              f"[{best_iter[i] + 1}]\t"
                              + "\t".join(_format_eval_result(x)
                                          for x in best_score_list[i]))
                    raise EarlyStopException(best_iter[i],
                                             best_score_list[i])
                _final_iteration_check(env, eval_name, i)

    # checkpoint/resume hooks (resilience/): the closure state above is
    # not reachable from outside, so expose explicit (de)serialization.
    # best_score_list entries are evaluation_result_list snapshots —
    # JSON turns their tuples into lists, which unpack the same way.
    def get_ckpt_state() -> Dict:
        return {"best_score": list(best_score),
                "best_iter": list(best_iter),
                "best_score_list": [
                    None if bsl is None else [list(x) for x in bsl]
                    for bsl in best_score_list],
                "cmp_flags": list(cmp_flags),
                "enabled": enabled[0],
                "first_metric": first_metric[0]}

    def set_ckpt_state(state: Dict) -> None:
        del best_score[:], best_iter[:], best_score_list[:]
        del cmp_op[:], cmp_flags[:]
        best_score.extend(state["best_score"])
        best_iter.extend(state["best_iter"])
        best_score_list.extend(
            None if bsl is None else [tuple(x) for x in bsl]
            for bsl in state["best_score_list"])
        cmp_flags.extend(bool(f) for f in state["cmp_flags"])
        cmp_op.extend((lambda x, y: x > y) if f else (lambda x, y: x < y)
                      for f in cmp_flags)
        enabled[0] = bool(state["enabled"])
        first_metric[0] = state["first_metric"]

    _callback.order = 30
    _callback.ckpt_key = "early_stopping"
    _callback.get_ckpt_state = get_ckpt_state
    _callback.set_ckpt_state = set_ckpt_state
    return _callback
